"""Flash attention forward kernel (Pallas TPU) with a recompute backward.

Blockwise online-softmax attention: scores are computed tile-by-tile in
VMEM and never materialized as a (T, T) matrix in HBM — the memory profile
that makes long context viable (the same recurrence as the pure-jnp
blockwise op in ``ops/attention.py``, which is this kernel's test oracle;
the reference repo has no attention at all, SURVEY.md section 2c).

Scope: forward pass as a kernel, tiled (block_q x block_k) with both
matmuls on the MXU in f32 accumulation. The backward is ``jax.vjp`` of the
dense reference — i.e. gradients recompute attention with XLA. That keeps
training correct everywhere while the fwd kernel carries the memory win
(eval/inference and activation-checkpointed training recompute forwards,
which is where the kernel runs). A fused flash backward kernel is the
natural next step and slots into the same ``custom_vjp``.

Composes with the mesh machinery: ``ring_attention_local`` accepts any
per-block attention update, and this kernel is what a production config
uses inside each ring step for long sequences.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pytorch_distributed_mnist_tpu.ops.attention import NEG_INF, full_attention


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                  scale: float, block_q: int, t_real: int):
    """One (batch*head, q-block) program: stream K/V blocks, online softmax.

    ``t_real``: valid sequence length; positions >= t_real are padding
    introduced to reach a tile-friendly block multiple and are masked out.
    """
    q = q_ref[0].astype(jnp.float32) * scale  # (BQ, D)
    t = k_ref.shape[1]
    nk = t // block_k
    iq = pl.program_id(1)
    masked = causal or t_real < t

    def body(j, carry):
        o, m, l = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BQ, BK)
        if masked:
            ki = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            keep = ki < t_real
            if causal:
                qi = iq * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0
                )
                keep &= qi >= ki
            s = jnp.where(keep, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if masked:
            p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        corr = jnp.where(m <= NEG_INF / 2, 0.0, jnp.exp(m - m_new))
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return o * corr + pv, m_new, l

    d = q_ref.shape[-1]
    o = jnp.zeros((block_q, d), jnp.float32)
    m = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    o, m, l = jax.lax.fori_loop(0, nk, body, (o, m, l))
    o_ref[0] = (o / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal: bool, scale: float | None,
                   interpret: bool | None):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, t, h, d = q.shape
    # Pad T up to a tile-friendly block multiple (never shrink the block to
    # a divisor of T — a prime T would degrade to block 1); padded K
    # positions are masked inside the kernel, padded Q rows sliced off.
    block = 128 if t >= 128 else ((t + 7) // 8) * 8
    t_pad = ((t + block - 1) // block) * block

    # (B, T, H, D) -> (B*H, Tp, D): one grid row per batch-head pair.
    def split(x):
        x = x.transpose(0, 2, 1, 3).reshape(b * h, t, d)
        if t_pad != t:
            x = jnp.pad(x, ((0, 0), (0, t_pad - t), (0, 0)))
        return x

    qh, kh, vh = split(q), split(k), split(v)
    kernel = functools.partial(
        _flash_kernel, block_k=block, causal=causal,
        scale=scale, block_q=block, t_real=t,
    )
    # NOTE: each program holds the full (Tp, D) K and V in VMEM, which caps
    # the sequence around T ~ 16k at D=64 f32 (~16 MB VMEM budget). Past
    # that, stream K/V through a third grid dimension — the online-softmax
    # carry already supports it; the ring (parallel/ring.py) also divides T
    # by the seq-axis size per device before this kernel sees it.
    out = pl.pallas_call(
        kernel,
        grid=(b * h, t_pad // block),
        in_specs=[
            pl.BlockSpec((1, block, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, t_pad, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, t_pad, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block, d), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b * h, t_pad, d), q.dtype),
        interpret=interpret,
    )(qh, kh, vh)
    return out[:, :t].reshape(b, h, t, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, causal, scale):
    return _flash_forward(q, k, v, causal, scale, None)


def _flash_fwd(q, k, v, causal, scale):
    return _flash(q, k, v, causal, scale), (q, k, v)


def _flash_bwd(causal, scale, residuals, g):
    # Recompute-based backward: differentiate the dense reference (same
    # math; see module docstring for the tradeoff).
    q, k, v = residuals
    _, vjp = jax.vjp(
        lambda a, b_, c: full_attention(a, b_, c, causal=causal, scale=scale),
        q, k, v,
    )
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = False,
                    scale: float | None = None):
    """Flash attention on ``(B, T, H, D)``; drop-in for ``full_attention``.

    Differentiable (recompute backward); off-TPU the kernel runs in
    interpreter mode so tests are hermetic.
    """
    return _flash(q, k, v, causal, scale)
