"""Fused Adam update as a Pallas TPU kernel, wrapped as an optax transform.

The reference's optimizer is ``torch.optim.Adam`` stepped once per batch
(``/root/reference/multi_proc_single_gpu.py:191, 92``) — a chain of
elementwise CUDA ops, each reading and writing HBM. Here the whole update
for a parameter leaf — moment EMAs, bias correction, epsilon-guarded scale
— is one kernel: every buffer is read once from HBM into VMEM and written
once, with ``input_output_aliases`` updating the moments in place. On the
memory-bound optimizer phase this halves-or-better the HBM traffic vs an
unfused op chain; XLA usually fuses most of it anyway, so the honest win is
guaranteed fusion + in-place moments, not a 10x.

``pallas_adam`` is a drop-in ``optax.GradientTransformation`` (same state
shape as ``optax.adam``: count + mu/nu trees) selected by
``--optimizer adam_pallas`` in the CLI. Off-TPU it runs the same kernel in
interpreter mode, so CPU tests exercise the identical code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import optax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# f32 VPU tile is (8, 128); 128 rows x 128 lanes x 4 B x 7 buffers ~ 0.5 MB
# of VMEM per grid step — comfortably under the ~16 MB budget.
_LANES = 128
_BLOCK_ROWS = 128


def _adam_kernel(h_ref, g_ref, m_ref, v_ref, delta_ref, m_out_ref, v_out_ref):
    """One block: delta = -lr * m_hat / (sqrt(v_hat) + eps); new moments.

    ``h_ref`` (SMEM) holds
    [lr, b1, b2, eps, 1/bias_corr1, 1/bias_corr2, 1-b1, 1-b2, eps_root].
    The bias
    corrections are step-dependent scalars computed in the enclosing jitted
    graph, so the kernel is step-agnostic; the complements ``1-b`` come
    precomputed in float64 because rounding ``1 - f32(0.999)`` in-kernel
    loses ~1e-5 relative vs optax's host-side arithmetic.
    """
    lr, b1, b2, eps = h_ref[0], h_ref[1], h_ref[2], h_ref[3]
    inv_bc1, inv_bc2 = h_ref[4], h_ref[5]
    c1, c2, eps_root = h_ref[6], h_ref[7], h_ref[8]
    g = g_ref[:]
    m = b1 * m_ref[:] + c1 * g
    v = b2 * v_ref[:] + c2 * g * g
    m_hat = m * inv_bc1
    v_hat = v * inv_bc2
    delta_ref[:] = -lr * m_hat / (jnp.sqrt(v_hat + eps_root) + eps)
    m_out_ref[:] = m
    v_out_ref[:] = v


def _should_interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_adam_leaf(g, m, v, hypers, *, interpret: bool | None = None):
    """Fused Adam for ONE parameter leaf of any shape/dtype.

    ``hypers``: f32[9] = [lr, b1, b2, eps, 1/bc1, 1/bc2, 1-b1, 1-b2,
    eps_root]. Returns
    ``(delta, new_m, new_v)`` with ``delta`` in optax's update convention
    (add it to the param). The leaf is flattened and zero-padded to a
    (rows, 128) f32 layout; padded lanes compute garbage that is sliced
    away (their moments stay zero because their gradients are zero).
    """
    if interpret is None:
        interpret = _should_interpret()
    shape = g.shape
    n = g.size
    rows = max(1, (n + _LANES - 1) // _LANES)
    # f32 sublane tile is 8 rows; cap the block at 128 rows but don't round
    # small leaves up to it (a (10,) bias pads to 8x128, not 128x128).
    rows = ((rows + 7) // 8) * 8
    block_rows = min(rows, _BLOCK_ROWS)
    rows = ((rows + block_rows - 1) // block_rows) * block_rows
    padded = rows * _LANES

    def prep(x):
        flat = jnp.ravel(x).astype(jnp.float32)
        return jnp.pad(flat, (0, padded - n)).reshape(rows, _LANES)

    g2, m2, v2 = prep(g), prep(m), prep(v)
    grid = (rows // block_rows,)
    block = pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
    out_shape = jax.ShapeDtypeStruct((rows, _LANES), jnp.float32)
    delta, m_new, v_new = pl.pallas_call(
        _adam_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # hypers, whole array
            block, block, block,
        ],
        out_specs=(block, block, block),
        out_shape=(out_shape, out_shape, out_shape),
        input_output_aliases={2: 1, 3: 2},  # m, v updated in place
        interpret=interpret,
    )(hypers, g2, m2, v2)

    def unprep(x, dtype):
        return jnp.ravel(x)[:n].reshape(shape).astype(dtype)

    # delta follows the gradient's dtype (optax update convention); moments
    # keep THEIR dtype — bf16 grads must not demote the f32 mu/nu (the EMA
    # increments would fall below bf16 resolution and the opt_state dtype
    # would flip after step 1, retracing the train step).
    return (unprep(delta, g.dtype), unprep(m_new, m.dtype),
            unprep(v_new, v.dtype))


def pallas_adam(
    learning_rate: float,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    eps_root: float = 0.0,
) -> optax.GradientTransformation:
    """optax transformation: Adam with the fused Pallas update kernel.

    State layout matches ``optax.scale_by_adam`` (count, mu, nu), so
    checkpoints are interchangeable with the stock ``adam`` optimizer.
    """

    def init(params):
        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params
        )
        return optax.ScaleByAdamState(
            count=jnp.zeros([], jnp.int32), mu=zeros,
            nu=jax.tree_util.tree_map(jnp.copy, zeros),
        )

    def update(updates, state, params=None):
        del params
        # optax renamed safe_int32_increment -> safe_increment; accept both
        # so the kernel runs on either side of the rename.
        _increment = getattr(optax, "safe_increment", None) \
            or optax.safe_int32_increment
        count = _increment(state.count)
        t = count.astype(jnp.float32)
        hypers = jnp.stack([
            jnp.asarray(learning_rate, jnp.float32),
            jnp.asarray(b1, jnp.float32),
            jnp.asarray(b2, jnp.float32),
            jnp.asarray(eps, jnp.float32),
            1.0 / (1.0 - jnp.asarray(b1, jnp.float32) ** t),
            1.0 / (1.0 - jnp.asarray(b2, jnp.float32) ** t),
            jnp.asarray(1.0 - b1, jnp.float32),  # complements in f64 first
            jnp.asarray(1.0 - b2, jnp.float32),
            jnp.asarray(eps_root, jnp.float32),
        ])
        flat_g, treedef = jax.tree_util.tree_flatten(updates)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_v = treedef.flatten_up_to(state.nu)
        out = [fused_adam_leaf(g, m, v, hypers)
               for g, m, v in zip(flat_g, flat_m, flat_v)]
        deltas = treedef.unflatten([o[0] for o in out])
        mu = treedef.unflatten([o[1] for o in out])
        nu = treedef.unflatten([o[2] for o in out])
        return deltas, optax.ScaleByAdamState(count=count, mu=mu, nu=nu)

    # The lr is already applied inside the kernel; the trailing no-op scale
    # makes the state pytree (ScaleByAdamState, EmptyState) structurally
    # identical to optax.adam = chain(scale_by_adam, scale(-lr)), so
    # checkpoints are interchangeable between the two optimizers.
    return optax.chain(
        optax.GradientTransformation(init, update), optax.scale(1.0)
    )
