"""Fused softmax-cross-entropy as a Pallas TPU kernel (forward + backward).

Parity target is the reference's ``F.cross_entropy(output, target)``
(``/root/reference/multi_proc_single_gpu.py:88``), whose CUDA implementation
is a fused log-softmax + NLL kernel pair. The XLA path
(``ops/loss.py``) already fuses well; this kernel makes the fusion a
guarantee and keeps the whole row pass — max, exp, sum, log, pick — in VMEM
with one HBM read of the logits per direction, the same honesty contract as
the fused Adam kernel (``ops/pallas/adam.py``): guaranteed single-pass, not
a 10x.

Forward: one block row-pass computes the per-example loss AND saves the
log-sum-exp, so the backward never re-reduces — ``dlogits = (exp(l - lse)
- onehot(label)) * g`` is a second single-pass kernel over the same rows.
No (B, C) softmax matrix is ever materialized in HBM in f32 beyond the
dlogits the optimizer actually needs.

Class-count restriction: ``C`` must fit one 128-lane tile (C <= 128 —
MNIST/FashionMNIST have 10). Wider heads would need a lane-tiled
online-softmax (the flash-attention pattern); ``fused_cross_entropy``
asserts rather than silently slowing down.

Off-TPU the identical kernel runs in Pallas interpret mode, so the CPU
suite exercises the same code path the chip compiles (conftest +
``tests_tpu/`` split, like the other kernels).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_LANES = 128
_BLOCK_ROWS = 128
_SUBLANE = 8


def _should_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _xent_fwd_kernel(c: int, logits_ref, label_ref, loss_ref, lse_ref):
    """One (R, 128) block: per-row loss and log-sum-exp.

    Lanes >= ``c`` are padding: masked to -inf before the max so they
    contribute nothing to the reduction. Padded *rows* (batch tail)
    compute garbage from zero logits; the wrapper slices them away.
    """
    l = logits_ref[:]
    col = jax.lax.broadcasted_iota(jnp.int32, l.shape, 1)
    valid = col < c
    l = jnp.where(valid, l, -jnp.inf)
    m = jnp.max(l, axis=1, keepdims=True)
    ex = jnp.where(valid, jnp.exp(l - m), 0.0)
    lse = m + jnp.log(jnp.sum(ex, axis=1, keepdims=True))
    picked = jnp.sum(
        jnp.where(col == label_ref[:], l, 0.0), axis=1, keepdims=True
    )
    # CE >= 0 analytically; clamp the same way the XLA oracle does
    # (ops/loss.py) so saturated logits never report a negative loss.
    loss_ref[:] = jnp.maximum(lse - picked, 0.0)
    lse_ref[:] = lse


def _xent_bwd_kernel(c: int, logits_ref, label_ref, lse_ref, g_ref, dl_ref):
    """dlogits = (softmax - onehot) * upstream, one pass over the block.

    Gated on the forward's ``max(lse - picked, 0)`` clamp exactly the way
    XLA differentiates it: gradient factor 1 where ``lse > picked``, 0
    where the clamp engaged (``lse < picked``, float-saturation artifact),
    and 0.5 at the exact tie — ``d/dx max(x, 0)`` at x == 0 splits evenly
    on the XLA path, so the fused gradient matches it even at
    float-saturated logits."""
    l = logits_ref[:]
    col = jax.lax.broadcasted_iota(jnp.int32, l.shape, 1)
    valid = col < c
    p = jnp.where(valid, jnp.exp(l - lse_ref[:]), 0.0)
    onehot = jnp.where(col == label_ref[:], 1.0, 0.0)
    picked = jnp.sum(jnp.where(col == label_ref[:], l, 0.0),
                     axis=1, keepdims=True)
    diff = lse_ref[:] - picked
    live = jnp.where(diff > 0.0, 1.0, jnp.where(diff == 0.0, 0.5, 0.0))
    dl_ref[:] = (p - onehot * valid) * g_ref[:] * live


def _pad_rows(b: int) -> int:
    r = min(_BLOCK_ROWS, ((b + _SUBLANE - 1) // _SUBLANE) * _SUBLANE)
    return r


def _prep(logits, labels):
    b, c = logits.shape
    if c > _LANES:
        raise ValueError(
            f"fused cross-entropy handles up to {_LANES} classes per "
            f"128-lane tile; got C={c} — use ops.loss.cross_entropy"
        )
    r = _pad_rows(b)
    n_blocks = (b + r - 1) // r
    bp = n_blocks * r
    # f32 boundary outside the kernel, same rationale as the XLA path's
    # optimization barrier: the reduction must not demote to bf16.
    l32 = jnp.zeros((bp, _LANES), jnp.float32)
    l32 = jax.lax.dynamic_update_slice(
        l32, logits.astype(jnp.float32), (0, 0))
    lab = jnp.zeros((bp, 1), jnp.int32)
    lab = jax.lax.dynamic_update_slice(
        lab, labels.astype(jnp.int32)[:, None], (0, 0))
    return l32, lab, r, n_blocks, bp, c


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def fused_cross_entropy_per_example(logits, labels):
    """Per-example loss, shape (B,) f32 — drop-in for the XLA oracle
    (``ops.loss.cross_entropy_per_example``), differentiable w.r.t.
    ``logits`` through a fused backward kernel."""
    loss, _ = _fwd_impl(logits, labels)
    return loss


def _fwd_impl(logits, labels, interpret=None):
    if interpret is None:
        interpret = _should_interpret()
    b = logits.shape[0]
    l32, lab, r, n_blocks, bp, c = _prep(logits, labels)
    loss, lse = pl.pallas_call(
        functools.partial(_xent_fwd_kernel, c),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((r, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((r, 1), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((r, 1), lambda i: (i, 0)),
            pl.BlockSpec((r, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bp, 1), jnp.float32),
            jax.ShapeDtypeStruct((bp, 1), jnp.float32),
        ],
        interpret=interpret,
    )(l32, lab)
    return loss[:b, 0], lse


def _fwd_rule(logits, labels):
    loss, lse = _fwd_impl(logits, labels)
    return loss, (logits, labels, lse)


def _bwd_rule(res, g):
    logits, labels, lse = res
    interpret = _should_interpret()
    b = logits.shape[0]
    l32, lab, r, n_blocks, bp, c = _prep(logits, labels)
    gp = jnp.zeros((bp, 1), jnp.float32)
    gp = jax.lax.dynamic_update_slice(
        gp, g.astype(jnp.float32)[:, None], (0, 0))
    dl = pl.pallas_call(
        functools.partial(_xent_bwd_kernel, c),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((r, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((r, 1), lambda i: (i, 0)),
            pl.BlockSpec((r, 1), lambda i: (i, 0)),
            pl.BlockSpec((r, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((r, _LANES), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((bp, _LANES), jnp.float32),
        interpret=interpret,
    )(l32, lab, lse, gp)
    dlogits = dl[:b, : logits.shape[1]].astype(logits.dtype)
    return dlogits, None


fused_cross_entropy_per_example.defvjp(_fwd_rule, _bwd_rule)


def fused_cross_entropy(logits, labels, mask=None):
    """Mean (or masked-mean) fused loss — signature parity with
    ``ops.loss.cross_entropy``. The reduction is ``ops.loss.masked_mean``,
    the single owner of the mean semantics for both impls (local import:
    ``loss`` only imports this module inside a function, so no cycle)."""
    from pytorch_distributed_mnist_tpu.ops.loss import masked_mean

    return masked_mean(fused_cross_entropy_per_example(logits, labels), mask)
