"""Pallas TPU kernels for the hot ops.

The reference gets its device kernels from cuDNN/cuBLAS through torch ops
(``/root/reference/multi_proc_single_gpu.py:87-92, 216``; SURVEY.md
section 2b "Device kernels"). On TPU, XLA compiles the jitted step — these
hand-written kernels cover the two places a fused kernel beats stock XLA:

- ``fused_adam``: the whole Adam update (moments + bias correction + step)
  as ONE VMEM-resident pass per parameter instead of XLA's chain of
  elementwise HLOs — one read and one write of each buffer, pure
  HBM-bandwidth win on the optimizer, which is the memory-bound part of
  small-model training.
- ``flash_attention``: blockwise online-softmax attention that never
  materializes the (T, T) score matrix in HBM — the long-context hot op;
  same math as ``ops/attention.py``'s blockwise reference, tiled for the
  MXU. ``sharded_flash_attention`` embeds it in GSPMD programs
  (batch x heads shard_map, the ``--tensor-parallel`` composition).
- ``fused_cross_entropy``: single-pass softmax-xent forward (loss + lse
  in VMEM) with a single-pass backward from the saved lse
  (``--loss fused``; ``ops/loss.py`` embeds it in GSPMD via a nested
  shard_map over the data axis).
- ``int8_dot_general``: int8 x int8 -> int32 MXU-native matmul (dynamic
  per-tensor symmetric scales, RNE rounding) behind a ``lax.dot_general``
  drop-in — the int8 serving precision's forward matmul, injected
  through the models' ``dot_general`` field so int8 buys chip clock,
  not just smaller transfers.

Every kernel auto-selects interpret mode off-TPU so the whole suite runs
hermetically on the virtual CPU mesh (tests/conftest.py).
"""

from pytorch_distributed_mnist_tpu.ops.pallas.adam import fused_adam_leaf, pallas_adam
from pytorch_distributed_mnist_tpu.ops.pallas.flash import (
    flash_attention,
    sharded_flash_attention,
)
from pytorch_distributed_mnist_tpu.ops.pallas.matmul_i8 import (
    int8_dot_general,
    matmul_i8,
    quantize_dynamic_i8,
)
from pytorch_distributed_mnist_tpu.ops.pallas.xent import (
    fused_cross_entropy,
    fused_cross_entropy_per_example,
)

__all__ = [
    "fused_adam_leaf",
    "pallas_adam",
    "flash_attention",
    "sharded_flash_attention",
    "fused_cross_entropy",
    "fused_cross_entropy_per_example",
    "int8_dot_general",
    "matmul_i8",
    "quantize_dynamic_i8",
]
