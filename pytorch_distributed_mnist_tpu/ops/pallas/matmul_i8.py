"""int8 MXU-native matmul as a Pallas TPU kernel.

The serving ``int8``/``int8w`` precision planes (``serve/programs.py``)
quantize WEIGHTS to int8 for the HBM/H2D byte win, then dequantize
on-chip and run the matmul in f32 — int8 buys memory, not MXU clock. On
TPU the MXU natively multiplies int8 x int8 into an int32 accumulator at
a multiple of the f32 issue rate; this kernel makes that the int8
plane's forward matmul: both operands quantize to symmetric per-tensor
int8 (round-to-nearest-even, the same rounding ``tm_quant_i8`` and the
fused plane's in-XLA twin use), one Pallas pass contracts them on the
MXU with ``preferred_element_type=jnp.int32`` (guide rule: never let the
accumulator dtype be inferred), and the int32 result rescales by the two
scales' product.

``int8_dot_general`` is a drop-in for ``lax.dot_general`` on the plain
Dense contraction — ``(..., K) x (K, N)``, no batch dims — which is
every ``nn.Dense`` in the model zoo; any other dimension_numbers falls
back to ``lax.dot_general`` unchanged, so wiring it through a model's
``dot_general`` field can never miscompute an einsum it wasn't built
for. It reaches the models through their ``dot_general`` constructor
field (``models/registry.py::model_accepts`` gates the injection), which
the server turns on for the ``int8`` serving plane only — the f32
baseline a canary shadows against never sees the kernel.

Numerics: dynamic per-tensor activation scales (``max|x| / 127``,
computed inside the jitted program — no host round-trip) on BOTH
operands. The weight operand arrives already dequantized by the int8
plane (per-leaf scales); re-quantizing per-tensor here costs one extra
rounding relative to the dequant path, which is why the kernel is
allclose-pinned against ``lax.dot_general`` rather than bitwise. Off-TPU
the identical kernel runs in Pallas interpret mode (the
``_should_interpret`` convention every kernel in this package follows).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from pytorch_distributed_mnist_tpu.ops.pallas.xent import _should_interpret

# int8 operands tile at (32, 128) on the MXU (int32 accumulators at
# (8, 128)); padding every dim up to these keeps Mosaic's layout happy
# and costs only zero rows/lanes, which contribute nothing to the
# integer accumulation.
_LANES = 128
_SUBLANE_I8 = 32
_BLOCK_M = 128

__all__ = ["int8_dot_general", "matmul_i8", "quantize_dynamic_i8"]


def _matmul_i8_kernel(a_ref, b_ref, out_ref):
    """One (bm, K) x (K, N) block product: int8 x int8 contracted on
    the MXU into the int32 accumulator — the whole point of the kernel;
    an inferred accumulator would silently round in f32."""
    out_ref[:] = jnp.dot(a_ref[:], b_ref[:],
                         preferred_element_type=jnp.int32)


def _pad_to(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def matmul_i8(a: jnp.ndarray, b: jnp.ndarray,
              interpret=None) -> jnp.ndarray:
    """``(M, K) int8 x (K, N) int8 -> (M, N) int32`` on the MXU.

    Shapes pad up to the int8 tile grid (M to the 32-sublane multiple,
    K and N to 128 lanes) outside the kernel; the grid runs one program
    instance per M block with the full K and N resident in VMEM —
    MNIST-scale operands (K <= a few thousand, N <= a few hundred) fit
    with room to spare, so no K-loop accumulation pass is needed.
    """
    if interpret is None:
        interpret = _should_interpret()
    if a.dtype != jnp.int8 or b.dtype != jnp.int8:
        raise ValueError(
            f"matmul_i8 takes int8 operands, got {a.dtype}/{b.dtype}")
    m, k = a.shape
    k2, n = b.shape
    if k != k2:
        raise ValueError(f"contraction mismatch: {a.shape} x {b.shape}")
    bm = min(_BLOCK_M, _pad_to(m, _SUBLANE_I8))
    mp = _pad_to(m, bm)
    kp = _pad_to(k, _LANES)
    np_ = _pad_to(n, _LANES)
    ap = jnp.zeros((mp, kp), jnp.int8).at[:m, :k].set(a)
    bp = jnp.zeros((kp, np_), jnp.int8).at[:k, :n].set(b)
    out = pl.pallas_call(
        _matmul_i8_kernel,
        grid=(mp // bm,),
        in_specs=[
            pl.BlockSpec((bm, kp), lambda i: (i, 0)),
            pl.BlockSpec((kp, np_), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bm, np_), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.int32),
        interpret=interpret,
    )(ap, bp)
    return out[:m, :n]


def quantize_dynamic_i8(x: jnp.ndarray):
    """Symmetric per-tensor dynamic quantization: ``(q_int8, scale)``
    with ``scale = max|x| / 127`` and round-to-nearest-even — the same
    rounding contract as the static-scale host/XLA quantizers
    (``serve/programs.py``), so the kernel's only numeric deltas vs the
    dequant path are the per-tensor scale granularity and the int32
    contraction."""
    x = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(x)), jnp.float32(1e-12)) / 127.0
    q = jax.lax.round(x / scale, jax.lax.RoundingMethod.TO_NEAREST_EVEN)
    return jnp.clip(q, -127.0, 127.0).astype(jnp.int8), scale


def int8_dot_general(lhs, rhs, dimension_numbers, precision=None,
                     preferred_element_type=None):
    """``lax.dot_general`` drop-in running the plain Dense contraction
    — ``(..., K) x (K, N)``, last-dim vs first-dim, no batch dims — as
    quantize + int8 MXU matmul + rescale. Every other contraction
    shape falls back to ``lax.dot_general`` verbatim.
    """
    (lc, rc), (lb, rb) = dimension_numbers
    plain = (not lb and not rb and rhs.ndim == 2
             and tuple(lc) == (lhs.ndim - 1,) and tuple(rc) == (0,))
    if not plain:
        return jax.lax.dot_general(
            lhs, rhs, dimension_numbers, precision=precision,
            preferred_element_type=preferred_element_type)
    out_dtype = preferred_element_type or jnp.result_type(lhs, rhs)
    lead = lhs.shape[:-1]
    a2 = lhs.reshape((-1, lhs.shape[-1]))
    qa, sa = quantize_dynamic_i8(a2)
    qb, sb = quantize_dynamic_i8(rhs)
    acc = matmul_i8(qa, qb)
    out = acc.astype(jnp.float32) * (sa * sb)
    return out.reshape(lead + (rhs.shape[-1],)).astype(out_dtype)
