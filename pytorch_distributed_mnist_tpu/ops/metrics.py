"""On-device metric accumulators.

Parity targets: ``Average`` (``/root/reference/multi_proc_single_gpu.py:28-43``)
— running weighted mean, ``update(value, n)`` accumulates ``sum += value*n``,
``count += n``, formatted to 6 decimals — and ``Accuracy`` (``:46-65``) —
argmax over the class axis, counts ``pred == target``, formatted as percent
with 2 decimals.

The TPU design differs deliberately from the reference's hot-loop behavior:
the reference calls ``.item()`` on device tensors every batch (``:94``,
``:62``), forcing a device->host sync per step. Here the accumulator state
(``MetricState``) is a pytree of device scalars updated *inside* the jitted
step; host transfer happens once per epoch when ``Average``/``Accuracy``
read it out (SURVEY.md section 3.2).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class MetricState(NamedTuple):
    """Device-resident accumulator: weighted loss sum, correct count, count."""

    loss_sum: jnp.ndarray  # f32 scalar: sum of per-example losses
    correct: jnp.ndarray  # f32 scalar: number of correct predictions
    count: jnp.ndarray  # f32 scalar: number of examples seen


def metrics_init() -> MetricState:
    zero = jnp.zeros((), jnp.float32)
    return MetricState(zero, zero, zero)


def metrics_update(
    state: MetricState,
    loss: jnp.ndarray,
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    mask: jnp.ndarray | None = None,
) -> MetricState:
    """Fold one batch into the accumulator (jit-friendly, no host sync).

    ``loss`` is the batch-*mean* loss (as produced by ``ops.loss.cross_entropy``);
    it is re-weighted by the number of *real* examples exactly like the
    reference's ``update(loss.item(), data.size(0))`` (``:94``, ``:41-43``).
    ``mask`` (0/1 per example) excludes eval-padding examples from all three
    counters, so padded samples are never double-counted — the reference
    never pads (its test loader just emits a ragged final batch).
    """
    if mask is None:
        n = jnp.asarray(labels.shape[0], jnp.float32)
        hit = (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
    else:
        mask = mask.astype(jnp.float32)
        n = jnp.sum(mask)
        hit = (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32) * mask
    return MetricState(
        loss_sum=state.loss_sum + loss.astype(jnp.float32) * n,
        correct=state.correct + jnp.sum(hit),
        count=state.count + n,
    )


def metrics_merge(a: MetricState, b: MetricState) -> MetricState:
    """Combine two accumulators (e.g. across devices after a psum gather)."""
    return MetricState(a.loss_sum + b.loss_sum, a.correct + b.correct, a.count + b.count)


class Average:
    """Host-side running weighted mean; formatting parity with reference ``Average``.

    ``__str__`` renders the mean to 6 decimal places, matching
    ``/root/reference/multi_proc_single_gpu.py:34-35``.
    """

    def __init__(self) -> None:
        self.sum = 0.0
        self.count = 0

    @property
    def average(self) -> float:
        if self.count == 0:
            return 0.0
        return self.sum / self.count

    def update(self, value: float, number: int = 1) -> None:
        self.sum += float(value) * number
        self.count += number

    def __str__(self) -> str:
        return f"{self.average:.6f}"


class Accuracy:
    """Host-side accuracy meter; formatting parity with reference ``Accuracy``.

    ``__str__`` renders a percentage with 2 decimals, matching
    ``/root/reference/multi_proc_single_gpu.py:52-53``.
    """

    def __init__(self) -> None:
        self.correct = 0
        self.count = 0

    @property
    def accuracy(self) -> float:
        if self.count == 0:
            return 0.0
        return self.correct / self.count

    def update(self, correct: int, count: int) -> None:
        self.correct += int(correct)
        self.count += int(count)

    def update_from_state(self, state: MetricState) -> None:
        self.correct += int(state.correct)
        self.count += int(state.count)

    def __str__(self) -> str:
        return f"{self.accuracy * 100:.2f}%"
