"""Numerical ops: loss functions and on-device metric accumulators."""

from pytorch_distributed_mnist_tpu.ops.loss import cross_entropy, cross_entropy_per_example
from pytorch_distributed_mnist_tpu.ops.metrics import (
    Average,
    Accuracy,
    MetricState,
    metrics_init,
    metrics_update,
    metrics_merge,
)

__all__ = [
    "cross_entropy",
    "cross_entropy_per_example",
    "Average",
    "Accuracy",
    "MetricState",
    "metrics_init",
    "metrics_update",
    "metrics_merge",
]
