"""Numerical ops: losses, on-device metric accumulators, attention kernels."""

from pytorch_distributed_mnist_tpu.ops.loss import cross_entropy, cross_entropy_per_example
from pytorch_distributed_mnist_tpu.ops.metrics import (
    Average,
    Accuracy,
    MetricState,
    metrics_init,
    metrics_update,
    metrics_merge,
)
from pytorch_distributed_mnist_tpu.ops.attention import (
    full_attention,
    online_softmax_block,
    online_softmax_finish,
    online_softmax_init,
)

__all__ = [
    "cross_entropy",
    "cross_entropy_per_example",
    "Average",
    "Accuracy",
    "MetricState",
    "metrics_init",
    "metrics_update",
    "metrics_merge",
    "full_attention",
    "online_softmax_block",
    "online_softmax_finish",
    "online_softmax_init",
]
