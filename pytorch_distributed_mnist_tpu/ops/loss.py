"""Loss functions.

Parity target: ``F.cross_entropy(output, target)`` at
``/root/reference/multi_proc_single_gpu.py:88`` — softmax cross-entropy over
integer class targets, *mean*-reduced over the batch. The mean reduction
matters for distributed semantics: DDP averages gradients across ranks, so a
per-rank batch-mean loss yields the global-batch-mean gradient. The TPU DP
step keeps the same convention (see ``parallel/collectives.py``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cross_entropy_per_example(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Per-example softmax cross-entropy with integer labels, shape (B,).

    Computed in float32 regardless of the model's compute dtype: the
    log-sum-exp reduction is the numerically delicate part, and float32 here
    costs nothing measurable on TPU (the FLOPs live in the matmuls).

    The optimization barrier is load-bearing: when logits arrive as
    ``astype(f32)`` of a bf16 model output, XLA:TPU's convert-folding will
    otherwise demote the fused exp/log chain back to bf16, inflating the
    reported loss by >10x on a converged model (observed: 0.0105 vs the true
    0.0004 on saturated CNN logits). The barrier pins the f32 boundary; it
    only costs the fusion of this epilogue into the preceding matmul.
    """
    logits = jax.lax.optimization_barrier(logits.astype(jnp.float32))
    logz = jax.nn.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    # CE = -log p >= 0 analytically; XLA:TPU's fused exp/log approximations
    # can drift a saturated logsumexp a few 1e-4 below the max logit, which
    # would surface as a (confusing) negative loss. Clamp at the true bound.
    return jnp.maximum(logz - label_logits, 0.0)


_IMPL = "xla"
_MESH = None
_MESH_AXIS = "data"


def set_loss_impl(name: str, mesh=None, data_axis: str = "data") -> None:
    """Select the cross-entropy implementation: ``xla`` (default) or
    ``fused`` (the Pallas kernel, ``ops/pallas/xent.py``). Resolved at
    trace time, so it must be set before the step functions are jitted
    (the CLI sets it before constructing the Trainer).

    ``mesh``: a pallas call under GSPMD batch sharding would be gathered,
    not partitioned; passing the mesh makes ``cross_entropy`` wrap the
    kernel in a nested ``shard_map`` over ``data_axis`` so each device
    runs it on its local batch shard — the standard way to embed a manual
    kernel in a GSPMD program. Leave ``mesh=None`` when the caller is
    ALREADY inside a shard_map (the explicit trainer mode): shard_maps do
    not nest over the same axis, and there the batch is local anyway."""
    if name not in ("xla", "fused"):
        raise ValueError(f"unknown loss impl {name!r}")
    global _IMPL, _MESH, _MESH_AXIS
    _IMPL = name
    _MESH = mesh if name == "fused" else None
    _MESH_AXIS = data_axis


def get_loss_impl() -> str:
    return _IMPL


def _fused_per_example(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    from pytorch_distributed_mnist_tpu.ops.pallas.xent import (
        fused_cross_entropy_per_example,
    )

    if _MESH is None or _MESH.shape[_MESH_AXIS] == 1:
        return fused_cross_entropy_per_example(logits, labels)
    size = _MESH.shape[_MESH_AXIS]
    if logits.shape[0] % size:
        # shard_map needs exact divisibility (GSPMD pads, manual regions
        # cannot); a ragged tail batch statically falls back to the XLA
        # impl — same values, different fusion.
        return cross_entropy_per_example(logits, labels)
    from jax.sharding import PartitionSpec as P

    return jax.shard_map(
        fused_cross_entropy_per_example,
        mesh=_MESH,
        in_specs=(P(_MESH_AXIS), P(_MESH_AXIS)),
        out_specs=P(_MESH_AXIS),
        check_vma=False,
    )(logits, labels)


def masked_mean(per_ex: jnp.ndarray, mask: jnp.ndarray | None) -> jnp.ndarray:
    """Mean (or masked mean) over per-example losses — the ONE place the
    reduction semantics live, shared by both loss impls so they cannot
    drift. Padded examples (0 in ``mask``) contribute nothing."""
    if mask is None:
        return jnp.mean(per_ex)
    mask = mask.astype(jnp.float32)
    return jnp.sum(per_ex * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def cross_entropy(
    logits: jnp.ndarray, labels: jnp.ndarray, mask: jnp.ndarray | None = None
) -> jnp.ndarray:
    """Mean softmax cross-entropy; with ``mask`` (0/1 per example), a masked
    mean so padded examples (eval batch padding) contribute nothing."""
    if _IMPL == "fused":
        per_ex = _fused_per_example(logits, labels)
    else:
        per_ex = cross_entropy_per_example(logits, labels)
    return masked_mean(per_ex, mask)
