"""Loss functions.

Parity target: ``F.cross_entropy(output, target)`` at
``/root/reference/multi_proc_single_gpu.py:88`` — softmax cross-entropy over
integer class targets, *mean*-reduced over the batch. The mean reduction
matters for distributed semantics: DDP averages gradients across ranks, so a
per-rank batch-mean loss yields the global-batch-mean gradient. The TPU DP
step keeps the same convention (see ``parallel/collectives.py``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cross_entropy_per_example(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Per-example softmax cross-entropy with integer labels, shape (B,).

    Computed in float32 regardless of the model's compute dtype: the
    log-sum-exp reduction is the numerically delicate part, and float32 here
    costs nothing measurable on TPU (the FLOPs live in the matmuls).
    """
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return logz - label_logits


def cross_entropy(
    logits: jnp.ndarray, labels: jnp.ndarray, mask: jnp.ndarray | None = None
) -> jnp.ndarray:
    """Mean softmax cross-entropy; with ``mask`` (0/1 per example), a masked
    mean so padded examples (eval batch padding) contribute nothing."""
    per_ex = cross_entropy_per_example(logits, labels)
    if mask is None:
        return jnp.mean(per_ex)
    mask = mask.astype(jnp.float32)
    return jnp.sum(per_ex * mask) / jnp.maximum(jnp.sum(mask), 1.0)
