"""Profiling hooks.

The reference has none — ``time`` is imported but never used
(``/root/reference/multi_proc_single_gpu.py:5``; SURVEY.md section 5
"Tracing/profiling: ABSENT"). The TPU build reports steps/sec and
images/sec/chip (the BASELINE.md metric) and can capture an XLA profiler
trace for xprof/tensorboard.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from typing import Callable, Dict, Optional

import jax


class StepTimer:
    """Throughput meter over explicitly measured phases.

    Only wall-time spent inside ``measure(...)`` blocks counts toward the
    rate, so training throughput is not diluted by eval/checkpoint time
    happening between measured phases (a phase-mixing bug in earlier
    revisions of ``cli.py`` that understated images/sec)."""

    def __init__(self, num_chips: Optional[int] = None) -> None:
        self.num_chips = num_chips or jax.device_count()
        self.reset()

    def reset(self) -> None:
        self.images = 0
        self.steps = 0
        self.seconds = 0.0
        self.last_images = 0
        self.last_seconds = 0.0

    @contextlib.contextmanager
    def measure(self, images: int):
        """Time the enclosed phase and attribute ``images`` to it.

        The caller must ensure device work is complete before the block
        exits (e.g. by folding metrics to host values inside it)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.last_seconds = time.perf_counter() - t0
            self.last_images = images
            self.seconds += self.last_seconds
            self.images += images
            self.steps += 1

    @property
    def elapsed(self) -> float:
        return self.seconds

    @property
    def images_per_sec(self) -> float:
        return self.images / max(self.elapsed, 1e-9)

    @property
    def images_per_sec_per_chip(self) -> float:
        return self.images_per_sec / self.num_chips

    @property
    def last_images_per_sec(self) -> float:
        """Rate of the most recent measured phase only — per-epoch
        throughput unpolluted by earlier epochs' compile time."""
        return self.last_images / max(self.last_seconds, 1e-9)

    @property
    def last_images_per_sec_per_chip(self) -> float:
        return self.last_images_per_sec / self.num_chips

    @property
    def steps_per_sec(self) -> float:
        return self.steps / max(self.elapsed, 1e-9)


class StagingLog:
    """Input data-plane observability: where does feeding the chip spend
    its time, and how much of it is hidden behind compute?

    The staging pipeline (``data/staging.py`` for the per-batch modes,
    the scan trainer's epoch prefetch) records one ``record_stage`` per
    staged batch/epoch — host-gather ms (the permutation copy) and H2D
    ms (``make_global_batch``'s sharded ``device_put``), tagged with
    whether it ran on a feeder thread — and the CONSUMER records how
    long it actually blocked waiting for staged data
    (``record_wait``). The difference is the overlap evidence:

    - ``overlap_fraction`` = 1 - blocked_ms / staging_ms: 0 on the
      synchronous path (every staging millisecond stalls the consumer,
      and the inline path records its own wall as wait so the figure is
      honest by construction), approaching 1 when the feeder fully
      hides staging behind compute;
    - ``feed_images_per_sec`` = images / staging wall: the feed-only
      throughput the input pipeline could sustain — the number a fast
      chip starves on when it exceeds the step rate. Stage walls time
      the ``device_put`` DISPATCH (JAX async dispatch returns before
      the transfer lands), so this figure is an upper bound here;
      ``bench.py --mode input`` re-derives its headline rate from a
      completion-blocked wall.

    Thread-safe: the feeder thread records stages while the consumer
    records waits. A process singleton (``staging_log``) follows the
    ``compile_log`` pattern: cli/bench attach it per run and reset it
    at entry.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._stages = 0
            self._pipelined_stages = 0
            self._host_ms = 0.0
            self._h2d_ms = 0.0
            self._images = 0
            self._waits = 0
            self._wait_ms = 0.0

    def record_stage(self, host_ms: float, h2d_ms: float, images: int,
                     pipelined: bool) -> None:
        """One staged batch (or stacked epoch): host-gather wall, H2D
        wall, the images it carried, and whether a feeder thread (not
        the consumer) ran it."""
        with self._lock:
            self._stages += 1
            if pipelined:
                self._pipelined_stages += 1
            self._host_ms += host_ms
            self._h2d_ms += h2d_ms
            self._images += images

    def record_wait(self, wait_ms: float) -> None:
        """Consumer-side blocked time for one batch handoff."""
        with self._lock:
            self._waits += 1
            self._wait_ms += wait_ms

    def summary(self) -> Dict:
        """Snapshot for cli summaries and the bench ``input_pipeline``
        block; all-zero (with ``overlap_fraction`` 0.0) when nothing
        was recorded."""
        with self._lock:
            staging_ms = self._host_ms + self._h2d_ms
            overlap = 0.0
            if staging_ms > 0:
                overlap = max(0.0, min(1.0, 1.0 - self._wait_ms / staging_ms))
            return {
                "stages": self._stages,
                "pipelined_stages": self._pipelined_stages,
                "host_ms": round(self._host_ms, 1),
                "h2d_ms": round(self._h2d_ms, 1),
                "consumer_wait_ms": round(self._wait_ms, 1),
                "overlap_fraction": round(overlap, 4),
                "images": self._images,
                "feed_images_per_sec": round(
                    self._images / max(staging_ms / 1e3, 1e-9), 1)
                if self._images else 0.0,
            }


# Singleton for the same reason as compile_log: one run, one input-plane
# story. cli.run and bench reset() it at entry.
staging_log = StagingLog()


def comm_overlap_fraction(step_ms: float, compute_ms: float,
                          comm_ms: float) -> Optional[float]:
    """How much of a step's measured communication cost is hidden behind
    its compute: ``1 - exposed/comm`` where ``exposed = max(step -
    compute, 0)`` — the three walls measured independently (the full
    step, a communication-free compute twin, a compute-free
    communication twin). 1.0 means the step costs no more than its
    compute (communication fully overlapped); 0.0 means every
    communication millisecond extends the step (fully serialized).
    Clamped to [0, 1] — the twins are separate measurements, so noise
    can push the raw ratio past either edge. ``None`` when there is no
    measurable communication (``comm_ms <= 0``) — a single-device world
    has nothing to overlap, and 0/0 must not report as overlap.

    Used by ``bench.py --mode zero``; unit-pinned in
    ``tests/test_bench_zero.py``.
    """
    if comm_ms is None or comm_ms <= 0 or step_ms is None \
            or compute_ms is None:
        return None
    exposed = max(float(step_ms) - float(compute_ms), 0.0)
    return round(max(0.0, min(1.0, 1.0 - exposed / float(comm_ms))), 4)


def per_tier_overlap_fractions(step_ms: float, compute_ms: float,
                               comm_ms_by_tier: dict) -> dict:
    """Per-tier overlap fractions for a multi-tier communication
    schedule (the DCN x ICI two-tier ZeRO step, ``bench.py --mode
    zero``): tier t's fraction is ``comm_overlap_fraction(step,
    compute, comm_t)`` — the step's WHOLE exposed time charged against
    that tier alone. Wall measurements cannot say WHICH tier's
    milliseconds the step hid, so each entry is the guaranteed-hidden
    lower bound: a tier scores above 0 only when the exposure is
    smaller than its own comm (some of it must have been hidden no
    matter how the exposure is attributed), and 1.0 only when the step
    costs no more than its compute.

    ``None`` entries propagate per tier (a zero-comm tier has nothing
    to overlap). Unit-pinned in ``tests/test_bench_zero.py``.
    """
    return {tier: comm_overlap_fraction(step_ms, compute_ms, comm)
            for tier, comm in comm_ms_by_tier.items()}


def stage_occupancy(stage_step_ms: dict) -> dict:
    """Per-stage occupancy of a streamed pipeline under full overlap:
    each stage's synchronous step wall over the BOTTLENECK stage's.

    A filled pipe retires one micro-batch per bottleneck-stage wall, so
    the slowest stage reads 1.0 (always busy) and every other stage is
    busy exactly its own wall's share of that clock and idles the rest —
    the imbalance this reports is the capacity a stage re-balancer
    (ROADMAP item 3) would recover. Empty/zero inputs return ``{}``:
    occupancy of a pipe that does no work is not 1.0.

    Used by ``bench.py --mode serve``'s ``pipeline_serving`` block;
    unit-pinned in ``tests/test_serve_mpmd.py``.
    """
    if not stage_step_ms:
        return {}
    slowest = max(float(v) for v in stage_step_ms.values())
    if slowest <= 0:
        return {}
    return {name: round(float(ms) / slowest, 4)
            for name, ms in stage_step_ms.items()}


class CompileLog:
    """Per-program compile observability: wall ms, XLA backend compiles,
    and persistent-cache hit/miss, attributed to named programs.

    jax reports compile activity through ``jax.monitoring`` events —
    ``/jax/compilation_cache/cache_hits`` / ``cache_misses`` fire per XLA
    compile request when the persistent cache is enabled, and the
    backend-compile duration event fires for every compile (a
    persistent-cache *hit* still reports a few ms there: that is the
    executable deserialization, not a compile). Listeners run on the
    thread doing the compiling, so attribution is thread-local: whatever
    program name the current thread has open via ``measure(name)`` owns
    the events — concurrent background precompiles (train/trainer.py)
    can't misfile each other's counts.

    ``cache_misses`` is the honest "programs actually compiled" counter:
    the acceptance bar for a warm start is zero misses, not zero
    backend-duration events.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._listening = False
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._programs: Dict[str, Dict] = {}
            self._totals = {"cache_hits": 0, "cache_misses": 0,
                            "backend_compiles": 0, "backend_compile_ms": 0.0}

    # -- jax.monitoring plumbing ------------------------------------------

    def _ensure_listening(self) -> None:
        from jax._src import monitoring

        # Under the lock: concurrent FIRST measures (the trainer's
        # background precompile threads) must not both register, or every
        # later compile event would be double-counted for the process
        # lifetime.
        with self._lock:
            if self._listening:
                return
            monitoring.register_event_listener(self._on_event)
            monitoring.register_event_duration_secs_listener(
                self._on_duration)
            self._listening = True

    def close(self) -> None:
        """Detach this log from jax.monitoring. The registered listeners
        hold a strong reference to the instance and fire on every future
        compile — fine for the module singleton, a leak for throwaway
        instances (tests), which should close() when done."""
        from jax._src import monitoring

        with self._lock:
            if not self._listening:
                return
            monitoring._unregister_event_listener_by_callback(self._on_event)
            monitoring._unregister_event_duration_listener_by_callback(
                self._on_duration)
            self._listening = False

    def _current(self) -> Optional[Dict]:
        return getattr(self._tls, "record", None)

    def _on_event(self, name: str, **kwargs) -> None:
        if name == "/jax/compilation_cache/cache_hits":
            key = "cache_hits"
        elif name == "/jax/compilation_cache/cache_misses":
            key = "cache_misses"
        else:
            return
        rec = self._current()
        with self._lock:
            self._totals[key] += 1
            if rec is not None:
                rec[key] += 1

    def _on_duration(self, name: str, secs: float, **kwargs) -> None:
        # The event was renamed across jax versions; accept both.
        if name not in ("/jax/core/compile/backend_compile_duration",
                        "/jax/core/compile/backend_compile_time_sec"):
            return
        rec = self._current()
        with self._lock:
            self._totals["backend_compiles"] += 1
            self._totals["backend_compile_ms"] += secs * 1e3
            if rec is not None:
                rec["backend_compiles"] += 1
                rec["backend_compile_ms"] += secs * 1e3

    # -- public API --------------------------------------------------------

    @contextlib.contextmanager
    def measure(self, program: str):
        """Attribute this thread's compile activity to ``program`` while
        the block runs; the record accumulates across repeat measures of
        the same name (e.g. precompile then first call)."""
        self._ensure_listening()
        with self._lock:
            rec = self._programs.setdefault(program, {
                "wall_ms": 0.0, "backend_compiles": 0,
                "backend_compile_ms": 0.0, "cache_hits": 0,
                "cache_misses": 0,
            })
        prev = self._current()
        self._tls.record = rec
        t0 = time.perf_counter()
        try:
            yield rec
        finally:
            dt = (time.perf_counter() - t0) * 1e3
            self._tls.record = prev
            with self._lock:
                rec["wall_ms"] += dt

    def stats(self) -> Dict:
        """``{"programs": {name: record}, "totals": {...}}`` snapshot.

        Each program record carries ``persistent_cache_hit``: True when
        every XLA compile request inside its measures was served from the
        persistent cache, False when any real compile happened, None when
        the persistent cache was disabled (no hit/miss events at all)."""
        with self._lock:
            programs = {}
            for name, rec in self._programs.items():
                rec = dict(rec)
                rec["wall_ms"] = round(rec["wall_ms"], 1)
                rec["backend_compile_ms"] = round(rec["backend_compile_ms"], 1)
                if rec["cache_hits"] or rec["cache_misses"]:
                    rec["persistent_cache_hit"] = rec["cache_misses"] == 0
                else:
                    rec["persistent_cache_hit"] = None
                programs[name] = rec
            totals = dict(self._totals)
        totals["backend_compile_ms"] = round(totals["backend_compile_ms"], 1)
        return {"programs": programs, "totals": totals}


# Process-wide singleton: entry points (cli/bench/tools) and the trainer's
# background precompile all feed one log, so a run's compile story lands in
# one place. Tests reset() it between cases.
compile_log = CompileLog()


class JsonlSink:
    """Append-only JSONL file shared by every metrics producer.

    One line per record, written atomically under a lock (the async
    checkpoint writer, watchdog timers, the serve batcher worker, and the
    reload watcher all record from their own threads). ``--metrics-file``
    resolves to ONE of these per process, so training epoch rows,
    supervision events, and serving stats land in the same file in the
    same format — a consumer tails one stream whichever mode produced it.
    """

    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = threading.Lock()
        self._warned = False
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)

    def write(self, record: Dict) -> None:
        """Append one record; raises on I/O failure (the per-epoch metric
        row keeps its historical fail-loudly contract)."""
        line = json.dumps(record)
        with self._lock:
            with open(self.path, "a") as f:
                f.write(line + "\n")

    def try_write(self, record: Dict) -> bool:
        """Best-effort append for callers on failure/supervision paths:
        a metrics-disk error (ENOSPC/EIO — plausible exactly when the
        run is already failing) must never mask the event being
        reported or break the agreed-exit machinery. Warns once."""
        try:
            self.write(record)
            return True
        except OSError as exc:
            with self._lock:
                first, self._warned = not self._warned, True
            if first:
                import sys

                print(f"WARNING: metrics sink {self.path!r} write failed "
                      f"({exc!r}); further events stay in memory only",
                      file=sys.stderr, flush=True)
            return False


class EventLog:
    """Append-only log of supervision/failure events for the run summary.

    The run-supervision layer (``runtime/supervision.py``) records every
    watchdog trip, poison-pill sent/received, retry, and quarantine here,
    and ``cli.run`` surfaces the snapshot as the summary's
    ``failure_events`` — so "what went wrong, when, in which phase" is one
    JSON block in the same place throughput and compile stats already
    live, instead of a grep through interleaved stderr. Thread-safe:
    watchdog timers and the async checkpoint writer record from their own
    threads.

    With a :class:`JsonlSink` attached (``set_sink``), every event is also
    appended to the sink as it happens — the ``--metrics-file`` stream —
    tagged with ``kind`` and ``source`` so train and serve events are
    distinguishable in the shared file.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._events = []
        self._sink: Optional[JsonlSink] = None
        self._source = "train"

    def set_sink(self, sink: Optional[JsonlSink],
                 source: str = "train") -> None:
        """Attach (or detach, ``None``) the shared JSONL sink. ``source``
        stamps each mirrored line so a file shared by a trainer and a
        serve process stays attributable."""
        with self._lock:
            self._sink = sink
            self._source = source

    def record(self, kind: str, detail: str, **fields) -> Dict:
        event = {"t": round(time.time(), 3), "kind": kind,
                 "detail": detail, **fields}
        with self._lock:
            self._events.append(event)
            sink, source = self._sink, self._source
        if sink is not None:
            # try_write: record() runs inside poison-pill delivery and
            # watchdog escalation — a sink I/O error must not mask the
            # failure being recorded.
            sink.try_write({**event, "source": source})
        return event

    def snapshot(self) -> list:
        with self._lock:
            return [dict(e) for e in self._events]

    def reset(self) -> None:
        """Clear events (and detach any sink: a re-entrant run must not
        keep appending to the previous run's metrics file)."""
        with self._lock:
            self._events.clear()
            self._sink = None
            self._source = "train"


# Singleton for the same reason as compile_log: one run, one failure story.
# cli.run resets it at entry so re-entrant runs report their own events.
failure_events = EventLog()


def record_world_shrunk(old_members, new_members, generation) -> Dict:
    """Record the elastic runtime's shrink event: this run is the
    rebuilt world after a host loss (``runtime/elastic.py``).

    One structured ``world_shrunk`` failure event carrying the old and
    new membership (stable host ids) and the rebuild generation — so
    the shrink shows up in the run summary's ``failure_events`` block
    AND, through the attached sink, as one line in the shared
    ``--metrics-file`` JSONL next to the epoch rows it explains (epoch
    metrics jump worlds exactly here). Called by
    ``elastic.note_rebuilt_world`` at run start, after ``cli.run``
    resets the log and attaches the sink."""
    old_members, new_members = list(old_members), list(new_members)
    return failure_events.record(
        "world_shrunk",
        f"world shrank from {len(old_members)} to {len(new_members)} "
        f"host(s) at generation {int(generation)}: members "
        f"{old_members} -> {new_members}; resumed from the last "
        f"published checkpoint",
        old_members=old_members, new_members=new_members,
        generation=int(generation))


def record_world_grown(old_members, new_members, generation) -> Dict:
    """The grow mirror of :func:`record_world_shrunk`: this run is the
    rebuilt world after a join rendezvous admitted a returned or
    replacement host (``runtime/elastic.py`` grow path). Same shape,
    distinct ``world_grown`` kind, so the metrics JSONL tells the two
    topology directions apart at a glance."""
    old_members, new_members = list(old_members), list(new_members)
    return failure_events.record(
        "world_grown",
        f"world grew from {len(old_members)} to {len(new_members)} "
        f"host(s) at generation {int(generation)}: members "
        f"{old_members} -> {new_members}; resumed from the last "
        f"published checkpoint (cross-world reshard onto the larger "
        f"world)",
        old_members=old_members, new_members=new_members,
        generation=int(generation))


def record_fleet_event(sink, kind: str, **fields) -> None:
    """Fleet-router lifecycle line (``fleet_quarantine`` /
    ``fleet_failover`` / ``fleet_rollout_*`` / ``fleet_canary_*`` /
    ``fleet_scale_*``) into a :class:`JsonlSink`.

    The sibling of :meth:`ServeLog.record_pool_event` one level up, but
    a free function taking the sink explicitly: the router
    (``serve/router.py``) is deliberately pure-stdlib and owns no
    ServeLog — it imports this lazily, only when ``--metrics-file``
    gave it a sink, so a router that never logs never touches the jax
    import chain. ``source: "router"`` keys the fleet tier's lines
    apart from the per-backend ``serve_*`` events riding the same
    stream."""
    if sink is None:
        return
    sink.try_write({"t": round(time.time(), 3), "kind": kind,
                    "source": "router", **fields})


def _percentile(sorted_vals: list, q: float) -> float:
    """Nearest-rank percentile over an already-sorted list (0 when empty).
    Nearest-rank (not interpolated) so p99 of a small sample is a latency
    that actually happened, never an optimistic blend."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(round(q * (len(sorted_vals) - 1)))))
    return float(sorted_vals[idx])


class ServeLog:
    """Serving observability: latency quantiles, batch-size histogram,
    queue depth, admission-control rejections, and hot reloads.

    The serve-side sibling of :class:`EventLog` + :class:`StepTimer`: the
    batcher worker records per-request latency, the engine records each
    executed bucket, the HTTP layer records rejections, and the reload
    watcher records checkpoint swaps — ``snapshot()`` is the ``/stats``
    payload. Thread-safe throughout (requests complete on the batcher
    worker thread while ``/stats`` reads from HTTP handler threads).

    Latency samples live in a bounded deque (recent-window quantiles, no
    unbounded growth under sustained load). With a :class:`JsonlSink`
    attached, ``write_stats()`` appends a ``{"kind": "serve_stats", ...}``
    snapshot line — the same ``--metrics-file`` stream training writes its
    epoch rows and failure events to.

    Two schema-ADDITIVE planes ride the same log:

    - a **rolling window** (``window_s``, default 60s): every snapshot
      carries a ``window`` block — p50/p95/p99 and requests/sec over
      the last ``window_s`` seconds ONLY — because the lifetime
      quantiles the block sits next to converge to history and cannot
      see current load (the autoscaler and an operator mid-incident
      both need "now", not "since boot"). ``window_stats()`` is the
      cheap probe the autoscaler samples.
    - **per-class counters** (priority serving): requests recorded with
      a ``klass`` land per-class latency quantiles, shed (503) and
      quota (429) counts in a ``classes`` block — present only when a
      class was ever recorded, so the single-class schema is unchanged.
    """

    #: Rolling-window sample bounds: latency samples and request
    #: timestamps kept for the window quantiles/rps. At 60s these cap
    #: the honest window at ~1k rps sustained — beyond that the window
    #: rps undercounts (documented, bounded memory wins).
    WINDOW_SAMPLES = 8192
    WINDOW_TIMES = 65536

    def __init__(self, max_samples: int = 8192,
                 window_s: float = 60.0) -> None:
        self._lock = threading.Lock()
        self._max_samples = max_samples
        self.window_s = float(window_s)
        self._now = time.monotonic  # overridable clock (tests)
        self._sink: Optional[JsonlSink] = None
        self._source = "serve"
        self._queue_depth_probe: Optional[Callable[[], int]] = None
        self._replicas_probe: Optional[Callable[[], Dict]] = None
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._latency = collections.deque(maxlen=self._max_samples)
            self._queue_wait = collections.deque(maxlen=self._max_samples)
            self._batch_hist: Dict[int, int] = {}
            self._counts = {"requests": 0, "images": 0, "batches": 0,
                            "rejected": 0, "reloads": 0,
                            "reload_failures": 0}
            # Rolling window: (t, latency_s) samples + bare timestamps
            # (for rps), pruned past window_s at record/snapshot time.
            self._win = collections.deque(maxlen=self.WINDOW_SAMPLES)
            self._win_times = collections.deque(maxlen=self.WINDOW_TIMES)
            self._t_reset = self._now()
            # Per-priority-class accounting (priority serving only):
            # stays empty — and out of the snapshot — when no request
            # ever carried a class.
            self._classes: Dict[str, Dict] = {}
            # Per-replica execution counters (multi-chip pool only): the
            # single-engine data plane records with replica=None and this
            # stays empty, keeping its snapshot/JSONL schema unchanged.
            self._replica_counts: Dict[str, Dict] = {}

    def set_sink(self, sink: Optional[JsonlSink],
                 source: str = "serve") -> None:
        with self._lock:
            self._sink = sink
            self._source = source

    def set_queue_depth_probe(self, probe: Optional[Callable[[], int]]) -> None:
        """Register a live queue-depth callable (the batcher's); read at
        snapshot time so ``/stats`` shows the instantaneous depth."""
        with self._lock:
            self._queue_depth_probe = probe

    def set_replicas_probe(self, probe: Optional[Callable[[], Dict]]) -> None:
        """Register the pool's per-replica snapshot callable (device,
        serving epoch, in-flight count per replica); merged into this
        log's per-replica batch counters at snapshot time so ``/stats``
        and the JSONL ``serve_stats`` lines carry one row per replica."""
        with self._lock:
            self._replicas_probe = probe

    # -- recorders (each from its owning thread) --------------------------

    def _class_rec(self, klass: str) -> Dict:
        """Per-class record (caller holds the lock)."""
        rec = self._classes.get(klass)
        if rec is None:
            rec = self._classes[klass] = {
                "requests": 0, "images": 0, "shed": 0,
                "quota_rejected": 0,
                "latency": collections.deque(maxlen=4096),
            }
        return rec

    def record_request(self, latency_s: float, queue_wait_s: float = 0.0,
                       images: int = 1,
                       klass: Optional[str] = None) -> None:
        now = self._now()
        with self._lock:
            self._counts["requests"] += 1
            self._counts["images"] += images
            self._latency.append(latency_s)
            self._queue_wait.append(queue_wait_s)
            self._win.append((now, latency_s))
            self._win_times.append(now)
            if klass is not None:
                rec = self._class_rec(klass)
                rec["requests"] += 1
                rec["images"] += images
                rec["latency"].append(latency_s)

    def _prune_window(self, now: float) -> None:
        """Drop window samples older than ``window_s`` (lock held)."""
        cutoff = now - self.window_s
        while self._win and self._win[0][0] < cutoff:
            self._win.popleft()
        while self._win_times and self._win_times[0] < cutoff:
            self._win_times.popleft()

    def window_stats(self) -> Dict:
        """The rolling-window block: latency quantiles + rps over the
        last ``window_s`` seconds only. Cheap enough to sample on the
        autoscaler's interval; also merged into every ``snapshot()``."""
        now = self._now()
        with self._lock:
            self._prune_window(now)
            lat = [s for _, s in self._win]
            n_requests = len(self._win_times)
            t_reset = self._t_reset
            probe = self._queue_depth_probe
        # The honest span: the full window once one has elapsed, the
        # log's lifetime before that (a fresh boot's rps must neither
        # be diluted over a window it hasn't lived nor inflated over
        # the microseconds since its first request), floored at 1s.
        span = max(1.0, min(self.window_s, now - t_reset))
        stats = self._quantiles(lat)
        depth = 0
        if probe is not None:
            try:
                depth = int(probe())
            except Exception:  # noqa: BLE001 - stats must never raise
                depth = -1
        return {
            "seconds": self.window_s,
            "rps": round(n_requests / span, 2),
            "queue_depth": depth,
            "p50_ms": stats["p50"], "p95_ms": stats["p95"],
            "p99_ms": stats["p99"], "count": stats["count"],
        }

    def record_batch(self, rows: int, bucket: int,
                     replica: Optional[str] = None) -> None:
        """One executed forward program: ``rows`` real examples padded up
        to ``bucket``, on ``replica`` (None = the single-engine plane)."""
        with self._lock:
            self._counts["batches"] += 1
            self._batch_hist[bucket] = self._batch_hist.get(bucket, 0) + 1
            if replica is not None:
                rec = self._replica_counts.setdefault(
                    replica, {"batches": 0, "images": 0,
                              "batch_histogram": {}})
                rec["batches"] += 1
                rec["images"] += rows
                hist = rec["batch_histogram"]
                hist[bucket] = hist.get(bucket, 0) + 1

    def record_rejection(self, klass: Optional[str] = None,
                         quota: bool = False) -> None:
        """One shed (503) or — with ``quota=True`` — one per-client
        quota refusal (429). Quota refusals never touch the lifetime
        ``rejected`` counter: they are the CLIENT's overload, not the
        server's, and conflating them would make the admission-control
        history unreadable."""
        with self._lock:
            if not quota:
                self._counts["rejected"] += 1
            if klass is not None:
                rec = self._class_rec(klass)
                rec["quota_rejected" if quota else "shed"] += 1

    def record_reload(self, path: str, epoch: int) -> None:
        with self._lock:
            self._counts["reloads"] += 1
            sink, source = self._sink, self._source
        if sink is not None:
            sink.try_write({"t": round(time.time(), 3),
                            "kind": "serve_reload", "path": path,
                            "epoch": epoch, "source": source})

    def record_reload_failure(self, path: str, detail: str) -> None:
        with self._lock:
            self._counts["reload_failures"] += 1
            sink, source = self._sink, self._source
        if sink is not None:
            sink.try_write({"t": round(time.time(), 3),
                            "kind": "serve_reload_failed", "path": path,
                            "detail": detail, "source": source})

    def record_pool_event(self, kind: str, **fields) -> None:
        """Sink-only serve lifecycle line (``serve_quarantine`` /
        ``serve_regroup`` / ``serve_resize``, and the shadow canary's
        ``serve_canary`` promote/rollback/reset transitions): the
        counters live in the pool's ``topology()`` / the canary's
        ``snapshot()`` blocks (surfaced via ``/stats``), so the
        single-engine snapshot schema stays untouched — this just lands
        the event in the shared ``--metrics-file`` stream next to the
        reloads it rides with."""
        with self._lock:
            sink, source = self._sink, self._source
        if sink is not None:
            sink.try_write({"t": round(time.time(), 3), "kind": kind,
                            "source": source, **fields})

    # -- consumers --------------------------------------------------------

    @staticmethod
    def _quantiles(samples) -> Dict[str, float]:
        vals = sorted(samples)
        ms = lambda s: round(s * 1e3, 3)  # noqa: E731
        return {
            "p50": ms(_percentile(vals, 0.50)),
            "p95": ms(_percentile(vals, 0.95)),
            "p99": ms(_percentile(vals, 0.99)),
            "mean": ms(sum(vals) / len(vals)) if vals else 0.0,
            "max": ms(vals[-1]) if vals else 0.0,
            "count": len(vals),
        }

    def snapshot(self) -> Dict:
        with self._lock:
            counts = dict(self._counts)
            latency = list(self._latency)
            queue_wait = list(self._queue_wait)
            hist = {str(k): v for k, v in sorted(self._batch_hist.items())}
            probe = self._queue_depth_probe
            replicas_probe = self._replicas_probe
            classes = {
                klass: {
                    "requests": rec["requests"],
                    "images": rec["images"],
                    "shed": rec["shed"],
                    "quota_rejected": rec["quota_rejected"],
                    "latency_ms": self._quantiles(list(rec["latency"])),
                }
                for klass, rec in sorted(self._classes.items())
            }
            replicas = {name: {**rec,
                               "batch_histogram": {
                                   str(k): v for k, v in
                                   sorted(rec["batch_histogram"].items())}}
                        for name, rec in self._replica_counts.items()}
        depth = 0
        if probe is not None:
            try:
                depth = int(probe())
            except Exception:  # noqa: BLE001 - stats must never raise
                depth = -1
        if replicas_probe is not None:
            try:
                for name, row in replicas_probe().items():
                    replicas.setdefault(
                        name, {"batches": 0, "images": 0,
                               "batch_histogram": {}}).update(row)
            except Exception:  # noqa: BLE001 - stats must never raise
                pass
        snap = {
            **counts,
            "queue_depth": depth,
            "latency_ms": self._quantiles(latency),
            "queue_wait_ms": self._quantiles(queue_wait),
            "batch_histogram": hist,
            # Rolling-window block (schema-ADDITIVE next to the
            # lifetime quantiles): what the load looks like NOW.
            "window": self.window_stats(),
        }
        # Per-priority-class rows appear only once a request carried a
        # class (priority serving) — classless servers' schema is
        # unchanged beyond the window block.
        if classes:
            snap["classes"] = classes
        # Per-replica rows appear only on the pooled data plane — the
        # single-engine snapshot/JSONL schema is unchanged.
        if replicas:
            snap["replicas"] = {k: replicas[k] for k in sorted(replicas)}
        return snap

    def write_stats(self, **extra) -> Dict:
        """Snapshot + append it to the attached sink (no-op without one);
        returns the snapshot either way."""
        snap = self.snapshot()
        with self._lock:
            sink, source = self._sink, self._source
        if sink is not None:
            sink.try_write({"t": round(time.time(), 3),
                            "kind": "serve_stats", "source": source,
                            **snap, **extra})
        return snap


@contextlib.contextmanager
def profile_trace(logdir: Optional[str]):
    """Capture a jax.profiler trace to ``logdir`` when set; no-op otherwise."""
    if not logdir:
        yield
        return
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def phase(name: str, **kwargs):
    """Named trace span (``jax.profiler.TraceAnnotation``) for one lifecycle
    phase — train/eval/checkpoint per epoch. Zero-cost when no trace is
    being captured; inside a ``--profile-dir`` capture the spans label the
    host timeline so the train/eval/checkpoint split is readable in
    xprof/perfetto instead of one undifferentiated epoch blob."""
    return jax.profiler.TraceAnnotation(name, **kwargs)
