"""Profiling hooks.

The reference has none — ``time`` is imported but never used
(``/root/reference/multi_proc_single_gpu.py:5``; SURVEY.md section 5
"Tracing/profiling: ABSENT"). The TPU build reports steps/sec and
images/sec/chip (the BASELINE.md metric) and can capture an XLA profiler
trace for xprof/tensorboard.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Optional

import jax


class StepTimer:
    """Throughput meter over explicitly measured phases.

    Only wall-time spent inside ``measure(...)`` blocks counts toward the
    rate, so training throughput is not diluted by eval/checkpoint time
    happening between measured phases (a phase-mixing bug in earlier
    revisions of ``cli.py`` that understated images/sec)."""

    def __init__(self, num_chips: Optional[int] = None) -> None:
        self.num_chips = num_chips or jax.device_count()
        self.reset()

    def reset(self) -> None:
        self.images = 0
        self.steps = 0
        self.seconds = 0.0
        self.last_images = 0
        self.last_seconds = 0.0

    @contextlib.contextmanager
    def measure(self, images: int):
        """Time the enclosed phase and attribute ``images`` to it.

        The caller must ensure device work is complete before the block
        exits (e.g. by folding metrics to host values inside it)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.last_seconds = time.perf_counter() - t0
            self.last_images = images
            self.seconds += self.last_seconds
            self.images += images
            self.steps += 1

    @property
    def elapsed(self) -> float:
        return self.seconds

    @property
    def images_per_sec(self) -> float:
        return self.images / max(self.elapsed, 1e-9)

    @property
    def images_per_sec_per_chip(self) -> float:
        return self.images_per_sec / self.num_chips

    @property
    def last_images_per_sec(self) -> float:
        """Rate of the most recent measured phase only — per-epoch
        throughput unpolluted by earlier epochs' compile time."""
        return self.last_images / max(self.last_seconds, 1e-9)

    @property
    def last_images_per_sec_per_chip(self) -> float:
        return self.last_images_per_sec / self.num_chips

    @property
    def steps_per_sec(self) -> float:
        return self.steps / max(self.elapsed, 1e-9)


class CompileLog:
    """Per-program compile observability: wall ms, XLA backend compiles,
    and persistent-cache hit/miss, attributed to named programs.

    jax reports compile activity through ``jax.monitoring`` events —
    ``/jax/compilation_cache/cache_hits`` / ``cache_misses`` fire per XLA
    compile request when the persistent cache is enabled, and the
    backend-compile duration event fires for every compile (a
    persistent-cache *hit* still reports a few ms there: that is the
    executable deserialization, not a compile). Listeners run on the
    thread doing the compiling, so attribution is thread-local: whatever
    program name the current thread has open via ``measure(name)`` owns
    the events — concurrent background precompiles (train/trainer.py)
    can't misfile each other's counts.

    ``cache_misses`` is the honest "programs actually compiled" counter:
    the acceptance bar for a warm start is zero misses, not zero
    backend-duration events.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._listening = False
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._programs: Dict[str, Dict] = {}
            self._totals = {"cache_hits": 0, "cache_misses": 0,
                            "backend_compiles": 0, "backend_compile_ms": 0.0}

    # -- jax.monitoring plumbing ------------------------------------------

    def _ensure_listening(self) -> None:
        from jax._src import monitoring

        # Under the lock: concurrent FIRST measures (the trainer's
        # background precompile threads) must not both register, or every
        # later compile event would be double-counted for the process
        # lifetime.
        with self._lock:
            if self._listening:
                return
            monitoring.register_event_listener(self._on_event)
            monitoring.register_event_duration_secs_listener(
                self._on_duration)
            self._listening = True

    def close(self) -> None:
        """Detach this log from jax.monitoring. The registered listeners
        hold a strong reference to the instance and fire on every future
        compile — fine for the module singleton, a leak for throwaway
        instances (tests), which should close() when done."""
        from jax._src import monitoring

        with self._lock:
            if not self._listening:
                return
            monitoring._unregister_event_listener_by_callback(self._on_event)
            monitoring._unregister_event_duration_listener_by_callback(
                self._on_duration)
            self._listening = False

    def _current(self) -> Optional[Dict]:
        return getattr(self._tls, "record", None)

    def _on_event(self, name: str, **kwargs) -> None:
        if name == "/jax/compilation_cache/cache_hits":
            key = "cache_hits"
        elif name == "/jax/compilation_cache/cache_misses":
            key = "cache_misses"
        else:
            return
        rec = self._current()
        with self._lock:
            self._totals[key] += 1
            if rec is not None:
                rec[key] += 1

    def _on_duration(self, name: str, secs: float, **kwargs) -> None:
        # The event was renamed across jax versions; accept both.
        if name not in ("/jax/core/compile/backend_compile_duration",
                        "/jax/core/compile/backend_compile_time_sec"):
            return
        rec = self._current()
        with self._lock:
            self._totals["backend_compiles"] += 1
            self._totals["backend_compile_ms"] += secs * 1e3
            if rec is not None:
                rec["backend_compiles"] += 1
                rec["backend_compile_ms"] += secs * 1e3

    # -- public API --------------------------------------------------------

    @contextlib.contextmanager
    def measure(self, program: str):
        """Attribute this thread's compile activity to ``program`` while
        the block runs; the record accumulates across repeat measures of
        the same name (e.g. precompile then first call)."""
        self._ensure_listening()
        with self._lock:
            rec = self._programs.setdefault(program, {
                "wall_ms": 0.0, "backend_compiles": 0,
                "backend_compile_ms": 0.0, "cache_hits": 0,
                "cache_misses": 0,
            })
        prev = self._current()
        self._tls.record = rec
        t0 = time.perf_counter()
        try:
            yield rec
        finally:
            dt = (time.perf_counter() - t0) * 1e3
            self._tls.record = prev
            with self._lock:
                rec["wall_ms"] += dt

    def stats(self) -> Dict:
        """``{"programs": {name: record}, "totals": {...}}`` snapshot.

        Each program record carries ``persistent_cache_hit``: True when
        every XLA compile request inside its measures was served from the
        persistent cache, False when any real compile happened, None when
        the persistent cache was disabled (no hit/miss events at all)."""
        with self._lock:
            programs = {}
            for name, rec in self._programs.items():
                rec = dict(rec)
                rec["wall_ms"] = round(rec["wall_ms"], 1)
                rec["backend_compile_ms"] = round(rec["backend_compile_ms"], 1)
                if rec["cache_hits"] or rec["cache_misses"]:
                    rec["persistent_cache_hit"] = rec["cache_misses"] == 0
                else:
                    rec["persistent_cache_hit"] = None
                programs[name] = rec
            totals = dict(self._totals)
        totals["backend_compile_ms"] = round(totals["backend_compile_ms"], 1)
        return {"programs": programs, "totals": totals}


# Process-wide singleton: entry points (cli/bench/tools) and the trainer's
# background precompile all feed one log, so a run's compile story lands in
# one place. Tests reset() it between cases.
compile_log = CompileLog()


class EventLog:
    """Append-only log of supervision/failure events for the run summary.

    The run-supervision layer (``runtime/supervision.py``) records every
    watchdog trip, poison-pill sent/received, retry, and quarantine here,
    and ``cli.run`` surfaces the snapshot as the summary's
    ``failure_events`` — so "what went wrong, when, in which phase" is one
    JSON block in the same place throughput and compile stats already
    live, instead of a grep through interleaved stderr. Thread-safe:
    watchdog timers and the async checkpoint writer record from their own
    threads.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._events = []

    def record(self, kind: str, detail: str, **fields) -> Dict:
        event = {"t": round(time.time(), 3), "kind": kind,
                 "detail": detail, **fields}
        with self._lock:
            self._events.append(event)
        return event

    def snapshot(self) -> list:
        with self._lock:
            return [dict(e) for e in self._events]

    def reset(self) -> None:
        with self._lock:
            self._events.clear()


# Singleton for the same reason as compile_log: one run, one failure story.
# cli.run resets it at entry so re-entrant runs report their own events.
failure_events = EventLog()


@contextlib.contextmanager
def profile_trace(logdir: Optional[str]):
    """Capture a jax.profiler trace to ``logdir`` when set; no-op otherwise."""
    if not logdir:
        yield
        return
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def phase(name: str, **kwargs):
    """Named trace span (``jax.profiler.TraceAnnotation``) for one lifecycle
    phase — train/eval/checkpoint per epoch. Zero-cost when no trace is
    being captured; inside a ``--profile-dir`` capture the spans label the
    host timeline so the train/eval/checkpoint split is readable in
    xprof/perfetto instead of one undifferentiated epoch blob."""
    return jax.profiler.TraceAnnotation(name, **kwargs)
