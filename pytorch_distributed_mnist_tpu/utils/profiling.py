"""Profiling hooks.

The reference has none — ``time`` is imported but never used
(``/root/reference/multi_proc_single_gpu.py:5``; SURVEY.md section 5
"Tracing/profiling: ABSENT"). The TPU build reports steps/sec and
images/sec/chip (the BASELINE.md metric) and can capture an XLA profiler
trace for xprof/tensorboard.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

import jax


class StepTimer:
    """Wall-clock throughput meter: images/sec and images/sec/chip."""

    def __init__(self, num_chips: Optional[int] = None) -> None:
        self.num_chips = num_chips or jax.device_count()
        self.reset()

    def reset(self) -> None:
        self.images = 0
        self.steps = 0
        self._start = time.perf_counter()

    def tick(self, batch_size: int) -> None:
        self.images += batch_size
        self.steps += 1

    @property
    def elapsed(self) -> float:
        return time.perf_counter() - self._start

    @property
    def images_per_sec(self) -> float:
        return self.images / max(self.elapsed, 1e-9)

    @property
    def images_per_sec_per_chip(self) -> float:
        return self.images_per_sec / self.num_chips

    @property
    def steps_per_sec(self) -> float:
        return self.steps / max(self.elapsed, 1e-9)


@contextlib.contextmanager
def profile_trace(logdir: Optional[str]):
    """Capture a jax.profiler trace to ``logdir`` when set; no-op otherwise."""
    if not logdir:
        yield
        return
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
