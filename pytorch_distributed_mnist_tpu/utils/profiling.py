"""Profiling hooks.

The reference has none — ``time`` is imported but never used
(``/root/reference/multi_proc_single_gpu.py:5``; SURVEY.md section 5
"Tracing/profiling: ABSENT"). The TPU build reports steps/sec and
images/sec/chip (the BASELINE.md metric) and can capture an XLA profiler
trace for xprof/tensorboard.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

import jax


class StepTimer:
    """Throughput meter over explicitly measured phases.

    Only wall-time spent inside ``measure(...)`` blocks counts toward the
    rate, so training throughput is not diluted by eval/checkpoint time
    happening between measured phases (a phase-mixing bug in earlier
    revisions of ``cli.py`` that understated images/sec)."""

    def __init__(self, num_chips: Optional[int] = None) -> None:
        self.num_chips = num_chips or jax.device_count()
        self.reset()

    def reset(self) -> None:
        self.images = 0
        self.steps = 0
        self.seconds = 0.0
        self.last_images = 0
        self.last_seconds = 0.0

    @contextlib.contextmanager
    def measure(self, images: int):
        """Time the enclosed phase and attribute ``images`` to it.

        The caller must ensure device work is complete before the block
        exits (e.g. by folding metrics to host values inside it)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.last_seconds = time.perf_counter() - t0
            self.last_images = images
            self.seconds += self.last_seconds
            self.images += images
            self.steps += 1

    @property
    def elapsed(self) -> float:
        return self.seconds

    @property
    def images_per_sec(self) -> float:
        return self.images / max(self.elapsed, 1e-9)

    @property
    def images_per_sec_per_chip(self) -> float:
        return self.images_per_sec / self.num_chips

    @property
    def last_images_per_sec(self) -> float:
        """Rate of the most recent measured phase only — per-epoch
        throughput unpolluted by earlier epochs' compile time."""
        return self.last_images / max(self.last_seconds, 1e-9)

    @property
    def last_images_per_sec_per_chip(self) -> float:
        return self.last_images_per_sec / self.num_chips

    @property
    def steps_per_sec(self) -> float:
        return self.steps / max(self.elapsed, 1e-9)


@contextlib.contextmanager
def profile_trace(logdir: Optional[str]):
    """Capture a jax.profiler trace to ``logdir`` when set; no-op otherwise."""
    if not logdir:
        yield
        return
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def phase(name: str, **kwargs):
    """Named trace span (``jax.profiler.TraceAnnotation``) for one lifecycle
    phase — train/eval/checkpoint per epoch. Zero-cost when no trace is
    being captured; inside a ``--profile-dir`` capture the spans label the
    host timeline so the train/eval/checkpoint split is readable in
    xprof/perfetto instead of one undifferentiated epoch blob."""
    return jax.profiler.TraceAnnotation(name, **kwargs)
