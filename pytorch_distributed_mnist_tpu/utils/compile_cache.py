"""Persistent XLA compile-cache wiring — ONE config-update path for every
entry point (``cli.run``, ``bench.py``, ``tools/northstar.py``, the test
harness).

First compilation of the jitted whole-epoch programs is the framework's
startup tax (VERDICT round 5: the entire 62.4s-vs-60s cold north-star gap
is compile time), and pjit-era practice treats the persistent compilation
cache + AOT lowering as the standard remedy. Before this module each entry
point carried its own copy of the config dance (``cli.run`` had one,
``bench.configure_jax`` another, the trainer none); they drifted. Now all
of them call :func:`configure`.

Resolution order for the cache directory:

1. explicit argument (the ``--compile-cache`` flag) — empty string means
   "explicitly disabled";
2. ``TPUMNIST_COMPILE_CACHE`` env var — empty string disables;
3. the AMBIENT process config: whatever a harness installed process-wide
   before the first ``configure()`` call (``tests/conftest.py`` installs
   its shared cache via :func:`configure_ambient`), so flag-less re-entrant
   ``run()`` calls keep the harness's cache instead of clobbering it;
4. the default ``<repo>/.xla_cache`` — the same dir ``tools/tpu_watch.sh``
   pre-warms and ``bench.py`` shares, so a production ``cli run`` benefits
   from any prior warmup with zero flags.

Cache entries are keyed by jax/jaxlib version, backend, and the serialized
program, so CPU test entries never collide with TPU entries and a jax
upgrade invalidates cleanly (stale entries are simply never hit again).
"""

from __future__ import annotations

import os
import threading
from typing import Optional, Tuple

import jax

ENV_VAR = "TPUMNIST_COMPILE_CACHE"

_lock = threading.Lock()
# (dir, min_compile_secs, min_entry_bytes) from before the first
# configure() — the config a flag-less run restores its disable path to.
_ambient: Optional[Tuple] = None
# True once a harness PINNED the ambient config via configure_ambient():
# flag-less runs then follow the harness (even "no cache"), instead of
# falling through to the repo default. tests/conftest.py pins "disabled"
# on jaxlibs whose in-process cache reuse is unsound (see its comment).
_pinned = False


def default_cache_dir() -> str:
    """``<repo>/.xla_cache`` (gitignored, shared with bench/tools/tests)."""
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.join(os.path.dirname(os.path.dirname(here)), ".xla_cache")


def _snapshot() -> Tuple:
    return (
        jax.config.jax_compilation_cache_dir,
        jax.config.jax_persistent_cache_min_compile_time_secs,
        jax.config.jax_persistent_cache_min_entry_size_bytes,
    )


def _resolve(flag: Optional[str]):
    """``(dir, explicit)``: the directory :func:`configure` would activate
    for ``flag`` (``None`` = disabled) and whether it was explicitly
    requested (flag/env/harness pin) rather than the implicit repo
    default. Explicit requests cache EVERY program (thresholds zeroed —
    the CPU-test programs compile sub-second and must still hit); the
    implicit default keeps jax's thresholds, which skip sub-second
    micro-programs (model-init one-offs) so a flag-less production run
    doesn't litter the dir with hundreds of tiny entries per run."""
    if flag is not None:
        return flag or None, True
    env = os.environ.get(ENV_VAR)
    if env is not None:
        return env or None, True
    if _pinned:
        return _ambient[0] or None, True
    if _ambient is not None and _ambient[0]:
        return _ambient[0], True
    return default_cache_dir(), False


def resolve_cache_dir(flag: Optional[str] = None) -> Optional[str]:
    """The directory :func:`configure` would activate for ``flag`` —
    resolution only, no config writes. ``None`` means caching disabled."""
    return _resolve(flag)[0]


def _apply(cache_dir: Optional[str], cache_everything: bool = True) -> None:
    if cache_dir:
        if jax.config.jax_compilation_cache_dir != cache_dir:
            # jax binds its cache object to the first dir that initializes
            # it, and an earlier run in this process may have compiled the
            # same programs under another dir (or none); reset so THIS
            # run's programs land in the requested dir. The in-memory jit
            # cache must go too — a program it already holds would never
            # reach XLA, so nothing would be written to the new dir.
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc,
            )

            _cc.reset_cache()
            jax.clear_caches()
        # Created eagerly (idempotent) so a first run's background
        # precompile threads never race the cache backend's own mkdir.
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        if cache_everything:
            # Cache every program, however small/fast-compiling (defaults
            # skip sub-second compiles, which covers most CPU-test
            # programs) — for explicitly-requested dirs (see _resolve).
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        else:
            _, amb_secs, amb_bytes = _ambient
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              amb_secs)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              amb_bytes)
    else:
        # Explicit disable (flag/env ""): the user asked for NO cache, not
        # for the ambient one — dir goes to None; the entry-size/compile-
        # time thresholds return to their pre-run values.
        _, amb_secs, amb_bytes = _ambient
        jax.config.update("jax_compilation_cache_dir", None)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          amb_secs)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          amb_bytes)


def configure(flag: Optional[str] = None) -> Optional[str]:
    """Activate the persistent cache for this run; returns the active dir
    (``None`` = disabled). Safe to call repeatedly in one process — a
    previous run's dir never leaks into a run that asked for another (or
    for none), and an unchanged dir never clears the in-memory jit cache.
    """
    global _ambient
    with _lock:
        if _ambient is None:
            _ambient = _snapshot()
        cache_dir, explicit = _resolve(flag)
        _apply(cache_dir, cache_everything=explicit)
        return cache_dir


def configure_ambient(cache_dir: Optional[str]) -> Optional[str]:
    """Harness-level entry (``tests/conftest.py``): activate ``cache_dir``
    AND pin the result as the ambient baseline — later flag-less
    :func:`configure` calls follow it exactly, INCLUDING a pinned
    "no cache" (``cache_dir`` empty/None), instead of falling through to
    the repo default."""
    global _ambient, _pinned
    with _lock:
        if _ambient is None:
            _ambient = _snapshot()
        if cache_dir:
            _apply(cache_dir)
        _ambient = _snapshot()
        _pinned = True
        return cache_dir or None


def active_cache_dir() -> Optional[str]:
    return jax.config.jax_compilation_cache_dir
