"""Utilities: rank-aware logging, profiling, seeding."""

from pytorch_distributed_mnist_tpu.utils.logging import log0, get_logger
from pytorch_distributed_mnist_tpu.utils.profiling import StepTimer, profile_trace

__all__ = ["log0", "get_logger", "StepTimer", "profile_trace"]
