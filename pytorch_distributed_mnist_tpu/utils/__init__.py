"""Utilities: rank-aware logging, profiling, compile-cache wiring, seeding."""

from pytorch_distributed_mnist_tpu.utils.logging import log0, get_logger
from pytorch_distributed_mnist_tpu.utils.profiling import (
    CompileLog,
    StepTimer,
    compile_log,
    profile_trace,
)

__all__ = ["log0", "get_logger", "StepTimer", "profile_trace",
           "CompileLog", "compile_log"]
