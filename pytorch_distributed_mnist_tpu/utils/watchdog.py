"""Host-side deadline and retry primitives for the run-supervision layer.

Multi-host agreement collectives (``runtime/supervision.py``) have no
native timeout: a peer that died outside an agreed phase leaves this host
blocked forever. ``run_with_deadline`` bounds any such call by running it
on a worker thread and joining with a timeout — the one threading pattern
that is compatible with the "no two collectives in flight at once"
invariant (docs/DESIGN.md section 6), because the main thread BLOCKS on
the join: process-wide there is still at most one collective executing.

On expiry the watchdog (a) invokes the caller's diagnostic dump, (b)
optionally arms a hard-exit timer so a process whose interpreter
teardown would itself block on the stuck collective still dies, and (c)
raises ``WatchdogTimeout`` in the caller. The worker thread stays
parked in the dead collective; callers must treat a ``WatchdogTimeout``
as fatal for the run (``already_agreed`` marks it as not needing — and
not safe for — any further collective participation).

``retry_with_backoff`` is the sibling primitive for the *retryable*
host-side failures (checkpoint publish rename on NFS, dataset mirror
fetch): bounded attempts, exponential backoff, jitter so a fleet of
hosts retrying a shared resource doesn't stampede in lockstep.
"""

from __future__ import annotations

import os
import random
import sys
import threading
import time
from typing import Callable, Optional, Tuple, Type

# Exit code for a watchdog hard-exit: distinct from SIGKILL's 137 and from
# ordinary failure 1, so a postmortem can tell "the watchdog shot this
# process" from "it crashed" at a glance. 75 = EX_TEMPFAIL.
HARD_EXIT_CODE = 75


def arm_hard_exit(delay: float, reason: str) -> None:
    """Arm a daemon timer that ``os._exit(HARD_EXIT_CODE)``s the process
    ``delay`` seconds from now unless it exits on its own first.

    The shared last resort for the two places a supervised process can
    get stuck on the way OUT: a watchdog-expired collective whose thread
    holds interpreter teardown hostage (``run_with_deadline``), and the
    distributed shutdown barrier that dead peers will never join
    (``supervision.escalate_exit``). Announces itself on stderr so the
    distinct exit code is explicable from the log.
    """
    t = threading.Timer(delay, lambda: os._exit(HARD_EXIT_CODE))
    t.daemon = True
    t.start()
    print(
        f"watchdog: {reason}; hard exit ({HARD_EXIT_CODE}) in {delay:g}s "
        f"unless the process unwinds first", file=sys.stderr, flush=True,
    )


class WatchdogTimeout(RuntimeError):
    """A supervised call exceeded its deadline.

    ``already_agreed`` tells the agreed-exit protocol NOT to attempt a
    poison-pill agreement on the way out: the peers this process would
    agree with are exactly the ones that failed to show up.
    """

    already_agreed = True

    def __init__(self, label: str, timeout: float) -> None:
        super().__init__(
            f"watchdog: {label} made no progress within {timeout:g}s"
        )
        self.label = label
        self.timeout = timeout


def run_with_deadline(
    fn: Callable,
    *,
    timeout: float,
    label: str,
    on_timeout: Optional[Callable[[], None]] = None,
    hard_exit_after: Optional[float] = None,
):
    """Run ``fn()`` with a deadline; return its result or raise.

    ``timeout <= 0`` disables supervision entirely: ``fn`` runs inline on
    the calling thread (the production default on real multi-host TPU,
    where a conservatively-sized deadline would still be a new way to
    kill a healthy-but-slow job).

    On expiry: ``on_timeout()`` runs first (diagnostics — it must not
    itself block or raise), then, when ``hard_exit_after`` is set, a
    daemon timer is armed that ``os._exit(HARD_EXIT_CODE)``s the process
    that many seconds later if it is still alive (interpreter teardown
    can block on the stuck collective's thread-state otherwise), then
    ``WatchdogTimeout`` is raised in the caller. ``fn``'s own exception
    propagates unchanged when it finishes in time.
    """
    if not timeout or timeout <= 0:
        return fn()
    outcome: dict = {}

    def _body() -> None:
        try:
            outcome["result"] = fn()
        except BaseException as exc:  # propagated by the joiner below
            outcome["error"] = exc

    t = threading.Thread(target=_body, daemon=True,
                         name=f"watchdog-{label}")
    t.start()
    t.join(timeout)
    if t.is_alive():
        if on_timeout is not None:
            try:
                on_timeout()
            except Exception as exc:  # diagnostics must never mask the abort
                print(f"watchdog: diagnostic dump for {label} failed: "
                      f"{exc!r}", file=sys.stderr, flush=True)
        if hard_exit_after and hard_exit_after > 0:
            arm_hard_exit(hard_exit_after, f"{label} timed out")
        raise WatchdogTimeout(label, timeout)
    if "error" in outcome:
        raise outcome["error"]
    return outcome.get("result")


def retry_with_backoff(
    fn: Callable,
    *,
    attempts: int,
    retry_on: Tuple[Type[BaseException], ...] = (OSError,),
    base_delay: float = 0.5,
    max_delay: float = 8.0,
    jitter: float = 0.5,
    on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
    sleep: Optional[Callable[[float], None]] = None,
    rng: Optional[random.Random] = None,
):
    """Call ``fn()`` up to ``attempts`` times; return its first success.

    Retries only on ``retry_on`` exceptions (anything else propagates
    immediately — a checksum mismatch is retryable, a programming error
    is not the retry loop's business). Delay before attempt ``k`` (1-based
    retries) is ``min(max_delay, base_delay * 2**(k-1))`` plus a uniform
    ``[0, jitter)`` second draw, so lockstep hosts retrying one shared
    mirror or filesystem de-synchronize. ``on_retry(attempt, exc, delay)``
    observes each scheduled retry (the supervision event log hooks in
    here); the final failure re-raises the last exception unchanged.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    if sleep is None:
        sleep = time.sleep  # late-bound: monkeypatched clocks apply
    draw = (rng or random).uniform
    last: Optional[BaseException] = None
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except retry_on as exc:
            last = exc
            if attempt == attempts:
                raise
            delay = min(max_delay, base_delay * (2 ** (attempt - 1)))
            delay += draw(0.0, jitter) if jitter > 0 else 0.0
            if on_retry is not None:
                on_retry(attempt, exc, delay)
            sleep(delay)
    raise last  # unreachable; keeps type-checkers honest
