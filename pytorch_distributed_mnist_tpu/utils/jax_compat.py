"""Runtime shims for older jax installs (currently 0.4.x).

The framework is written against the modern public surface — ``jax.shard_map``
with ``check_vma``, a differentiable ``lax.optimization_barrier`` — but the
pinned environment may ship a jax where those are still
``jax.experimental.shard_map.shard_map(check_rep=...)`` and a barrier with no
AD rule (it gained one upstream later; the rule is the identity/linear one,
matching the barrier's semantics of "same values, no fusion across").

``install()`` is idempotent and a no-op on a jax that already has the
modern surface; the package ``__init__`` calls it, so every entry point
(cli, bench, tools, tests) sees one consistent API. Nothing here changes
numerics: the shim translates names/kwargs and registers the same linear
AD rule jax itself adopted.
"""

from __future__ import annotations

import functools

import jax

_installed = False


def _shard_map_shim():
    """``jax.shard_map`` accepting ``check_vma`` on a jax whose shard_map
    still lives in ``jax.experimental`` under the ``check_rep`` spelling."""
    from jax.experimental.shard_map import shard_map as _legacy

    @functools.wraps(_legacy)
    def shard_map(f=None, /, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        if f is None:  # partial form: jax.shard_map(mesh=..., ...)(f)
            return lambda fn: shard_map(fn, **kwargs)
        return _legacy(f, **kwargs)

    return shard_map


def _register_optimization_barrier_ad() -> None:
    """The identity JVP/transpose jax later added upstream: the barrier is
    linear (it only pins values against compiler fusion), so tangents and
    cotangents pass through their own barrier."""
    from jax._src.interpreters import ad
    from jax._src.lax import lax as _lax_internal

    prim = _lax_internal.optimization_barrier_p
    if prim in ad.primitive_jvps:
        return

    def _jvp(primals, tangents):
        tangents = [ad.instantiate_zeros(t) for t in tangents]
        return prim.bind(*primals), prim.bind(*tangents)

    def _transpose(cts, *primals):
        del primals
        cts = [ad.instantiate_zeros(ct) for ct in cts]
        return prim.bind(*cts)

    ad.primitive_jvps[prim] = _jvp
    ad.primitive_transposes[prim] = _transpose


def _axis_size(axis_name):
    """Static mesh-axis size from inside a shard_map/pmap body — the
    ``lax.axis_size`` jax later added; on 0.4.x the same integer lives on
    the trace context's axis env."""
    from jax._src import core

    return core.axis_frame(axis_name)


COLLECTIVE_TIMEOUT_FLAGS = (
    " --xla_cpu_collective_call_terminate_timeout_seconds=600"
    " --xla_cpu_collective_timeout_seconds=600"
)
_collective_flags_supported = None


def supported_collective_timeout_flags() -> str:
    """``COLLECTIVE_TIMEOUT_FLAGS`` when this jaxlib's XLA knows them,
    else ``""``. XLA *aborts the process* on an unknown flag at backend
    init (parse_flags_from_env is fatal), so callers must probe in a
    throwaway child before appending them to XLA_FLAGS. ~1s, cached for
    the process. (tests/conftest.py carries its own copy of this probe
    because it must run before anything imports jax.)"""
    global _collective_flags_supported
    if _collective_flags_supported is None:
        import subprocess
        import sys

        probe = ("import os; os.environ['XLA_FLAGS'] = %r; "
                 "from jaxlib import xla_client; xla_client.make_cpu_client()"
                 % COLLECTIVE_TIMEOUT_FLAGS.strip())
        try:
            _collective_flags_supported = subprocess.run(
                [sys.executable, "-c", probe], capture_output=True,
                timeout=120,
            ).returncode == 0
        except (OSError, subprocess.SubprocessError):
            _collective_flags_supported = False
    return COLLECTIVE_TIMEOUT_FLAGS if _collective_flags_supported else ""


def install() -> None:
    global _installed
    if _installed:
        return
    _installed = True
    if not hasattr(jax, "shard_map"):
        jax.shard_map = _shard_map_shim()
    if not hasattr(jax.lax, "axis_size"):
        jax.lax.axis_size = _axis_size
    _register_optimization_barrier_ad()
