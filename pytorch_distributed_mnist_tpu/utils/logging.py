"""Rank-aware logging.

The reference prints epoch metrics from **every** rank
(``/root/reference/multi_proc_single_gpu.py:238-242``), so a 4-GPU run
prints everything 4 times. Here the default is process-0-only printing
(SURVEY.md section 5 observability note); ``all_ranks=True`` restores the
reference behavior for debugging.
"""

from __future__ import annotations

import logging
import sys

import jax


def log0(*args, all_ranks: bool = False, **kwargs) -> None:
    """print() from process 0 only (or all ranks when asked)."""
    if all_ranks or jax.process_index() == 0:
        print(*args, **kwargs)
        sys.stdout.flush()


def get_logger(name: str = "tpu_mnist") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(
            logging.Formatter("%(asctime)s [p%(process)d] %(levelname)s %(message)s")
        )
        logger.addHandler(h)
        logger.setLevel(logging.INFO)
    return logger
