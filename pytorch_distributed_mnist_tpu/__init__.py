"""tpu-mnist: a TPU-native (JAX/XLA/pjit) distributed training framework.

Re-implements, TPU-first, every capability of the reference
``flybirdtian/pytorch_distributed_mnist`` (``multi_proc_single_gpu.py``):

- data-parallel training over a ``jax.sharding.Mesh`` (DDP's NCCL allreduce
  becomes an XLA AllReduce / ``lax.psum`` over the mesh's ``data`` axis),
- ``DistributedSampler``-style disjoint per-host sharding with per-epoch
  reshuffle,
- step-decay LR schedule, per-epoch checkpointing with best-model tracking,
  ``--resume`` and ``--evaluate``,
- a CLI with flag parity,

plus the tests, profiling, and benchmarks the reference lacks. The compute
path is JAX/XLA (jit + sharding + Pallas); the host-side data path can be
backed by the optional native C++ loader under ``native/`` when built.
"""

__version__ = "0.1.0"

# Before any framework module touches jax: shim older jax installs up to
# the surface this package is written against (see utils/jax_compat.py).
from pytorch_distributed_mnist_tpu.utils import jax_compat as _jax_compat

_jax_compat.install()

from pytorch_distributed_mnist_tpu.train.state import TrainState, create_train_state
from pytorch_distributed_mnist_tpu.train.trainer import Trainer
from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.parallel.mesh import make_mesh

__all__ = [
    "TrainState",
    "create_train_state",
    "Trainer",
    "get_model",
    "make_mesh",
    "__version__",
]
