"""``python -m pytorch_distributed_mnist_tpu`` — single entry point.

Replaces the reference's two launch modes selected by editing source
(``/root/reference/multi_proc_single_gpu.py:353-359``, ``README.md:10-35``):
on a real TPU pod the runtime is already one process per host, so nothing
needs spawning and no ``--local_rank`` is injected; ``--spawn N``
(parallel/launcher.py) provides the reference's ``mp.spawn`` mode as a flag
for local N-host simulation.
"""

from pytorch_distributed_mnist_tpu.cli import main

if __name__ == "__main__":
    main()
