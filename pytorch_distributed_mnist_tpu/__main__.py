"""``python -m pytorch_distributed_mnist_tpu`` — single entry point.

Replaces the reference's two launch modes selected by editing source
(``/root/reference/multi_proc_single_gpu.py:353-359``, ``README.md:10-35``):
on TPU the runtime is already one process per host, so there is nothing to
spawn and no ``--local_rank`` to inject.
"""

from pytorch_distributed_mnist_tpu.cli import main

if __name__ == "__main__":
    main()
