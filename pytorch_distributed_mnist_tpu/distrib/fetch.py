"""Serve-side delta fetch: the ``CheckpointWatcher``'s manifest loader.

``DeltaFetcher.load`` has the exact ``load_params_for_serving``
signature — ``(path, template_state) -> (params, epoch)`` — and plugs
into the watcher's ``loader=`` seam, so manifest discovery, the epoch
ordering rule, the validate_fn layout gate, and the one atomic
``swap_params`` install are all UNCHANGED machinery; only the
bytes-acquisition step differs:

- diff the manifest's chunk lists against the local store inventory
  and the previous install's per-leaf hashes;
- fetch ONLY missing chunks — peer backends first (``GET
  /chunks/<hash>``, the gossip plane: a fleet publish costs the source
  O(chunks), not O(replicas)), source directory as fallback — each
  verified against its digest before entering the local store;
- patch only the DIRTY leaves of the cached host tree and re-quantize
  only those (clean leaves ride through as the previous install's
  ``QuantLeaf``/cast leaves — PR 13's idempotent
  ``ServePrecision.quantize`` passes them through untouched, which the
  requantize pin test asserts by object identity);
- serving fetches only ``params`` leaves: optimizer moments never ship
  to the fleet (two thirds of an Adam checkpoint's bytes).

Failure taxonomy: a torn manifest raises ``JSONDecodeError`` (content
damage -> watcher permanent-skip, resume quarantine); a chunk missing
from every peer AND the source raises a ValueError whose message says
``missing chunk`` — absence for THIS publish, permanent-skip at the
watcher until a newer manifest appears, exactly the ISSUE's
torn-publish contract. The server keeps answering on its installed
params throughout.
"""

from __future__ import annotations

import http.client
import urllib.request
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from pytorch_distributed_mnist_tpu.distrib.cas import (
    ChunkStore,
    _digest,
    is_manifest,
    read_manifest,
)

PARAMS_PREFIX = "['params']"


# Streaming read granularity for chunk fetches: small enough that a
# torn connection loses at most one piece, large enough that syscall
# overhead stays invisible against MB-scale chunks.
_FETCH_PIECE_BYTES = 1 << 16


def fetch_chunk_http(base_url: str, digest: str,
                     timeout_s: float = 5.0, max_resumes: int = 3) -> bytes:
    """One peer chunk GET with ranged resume: the body streams in
    pieces, and a mid-body disconnect retries with ``Range: bytes=N-``
    from the partial offset instead of re-downloading from zero —
    content addressing makes the bytes behind a digest immutable, so
    splicing ranges across attempts is safe by construction (and the
    digest verify in ``_obtain`` backstops it regardless). A peer that
    ignores Range (a plain 200 after a resume request) resets the
    buffer and restarts. Raises on a failure before the first byte, a
    resume that makes no progress, or exhausted resumes — the caller
    falls through to the next peer / the source dir."""
    url = f"{base_url.rstrip('/')}/chunks/{digest}"
    buf = bytearray()
    resumes = 0
    while True:
        req = urllib.request.Request(url)
        if buf:
            req.add_header("Range", f"bytes={len(buf)}-")
        got = 0
        expected = None
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                if buf and getattr(resp, "status", 200) != 206:
                    # Peer ignored the Range header: the body restarts
                    # at byte 0, so the splice buffer must too.
                    del buf[:]
                length = resp.headers.get("Content-Length")
                if length is not None:
                    expected = len(buf) + int(length)
                while True:
                    piece = resp.read(_FETCH_PIECE_BYTES)
                    if not piece:
                        break
                    buf += piece
                    got += len(piece)
            if expected is None or len(buf) == expected:
                return bytes(buf)
            # Short body against the advertised Content-Length: a
            # mid-body tear that http.client reports as plain EOF on
            # piecewise read(amt) — NOT IncompleteRead (that only
            # fires on an unsized read()). Fall through to resume.
        except http.client.IncompleteRead as exc:
            # Keep what arrived before the tear; resume from there.
            buf += exc.partial
            got += len(exc.partial)
        except (OSError, http.client.HTTPException):
            if not buf:
                raise  # failed before any byte: plain peer failure
        resumes += 1
        if got == 0 or resumes > max_resumes:
            raise OSError(
                f"torn chunk fetch {digest} from {base_url}: "
                f"{len(buf)} byte(s) after {resumes} attempt(s)")


def _zeroed() -> Dict[str, int]:
    return {"dirty_leaves": 0, "clean_leaves": 0, "chunks_fetched": 0,
            "bytes_fetched": 0, "bytes_peer": 0, "bytes_source": 0,
            "bytes_local": 0, "full_loads": 0, "delta_loads": 0}


class DeltaFetcher:
    """Stateful manifest loader for one watch directory.

    ``directory`` is the watcher's checkpoint directory: manifests
    arrive there (trainer publish on a shared fs, or a router
    ``/rollout`` manifest copy) and fetched chunks are installed into
    ``<directory>/chunks/`` — which is exactly what this backend's own
    ``GET /chunks/<hash>`` endpoint serves, so every fetcher is also a
    gossip seeder the moment its fetch completes.

    ``precision`` (a ``ServePrecision``) opts into fetch-side
    quantization: the returned tree carries the previous install's
    quantized leaves for clean params and raw f32 for dirty ones, so
    the engine's ``_place`` (idempotent quantize) re-quantizes only
    what changed. Leave it None when multiple planes share the loader
    (a shadow canary's f32 baseline must never receive pre-quantized
    leaves); the delta fetch itself still applies.
    """

    def __init__(
        self,
        directory: str,
        *,
        precision=None,
        peers: Sequence[str] = (),
        source_dir: Optional[str] = None,
        workers: int = 4,
        timeout_s: float = 5.0,
    ) -> None:
        self.store = ChunkStore(directory)
        self.peers = [p for p in peers if p]
        self.source = ChunkStore(source_dir) if source_dir else None
        self._precision = precision
        self._workers = workers
        self._timeout = timeout_s
        # Per-leaf state from the previous successful manifest load:
        # chunk-hash tuple (the diff key) and the installed leaf value
        # (QuantLeaf / cast array / f32 array — whatever the precision
        # hook produced), keyed by manifest leaf name.
        self._hashes: Dict[str, tuple] = {}
        self._values: Dict[str, object] = {}
        self.total = _zeroed()
        self.last = _zeroed()

    # -- chunk acquisition --------------------------------------------------

    def _obtain(self, digest: str, stats: Dict[str, int]) -> None:
        """Ensure ``digest`` is in the local store: local hit, else peers
        (rotation keyed by the digest spreads a fleet's pulls across
        seeders), else the source directory. Verified-on-put, so corrupt
        peer bytes read as a miss, not an install."""
        if self.store.has(digest):
            return
        n = len(self.peers)
        start = int(digest[:8], 16) % n if n else 0
        for k in range(n):
            peer = self.peers[(start + k) % n]
            try:
                data = fetch_chunk_http(peer, digest, self._timeout)
                if _digest(data) != digest:
                    raise ValueError("digest mismatch")
                self.store.put(digest, data)
                stats["chunks_fetched"] += 1
                stats["bytes_fetched"] += len(data)
                stats["bytes_peer"] += len(data)
                return
            except Exception:  # noqa: BLE001 - any peer failure: next
                continue
        if self.source is not None and self.source.has(digest):
            data = self.source.get(digest)
            self.store.put(digest, data)
            stats["chunks_fetched"] += 1
            stats["bytes_fetched"] += len(data)
            stats["bytes_source"] += len(data)
            return
        raise ValueError(
            f"missing chunk {digest}: not in the local store, "
            f"{len(self.peers)} peer(s), or the source dir — skipping "
            f"this publish until a newer manifest appears")

    # -- the loader seam ----------------------------------------------------

    def load(self, path: str, template_state) -> Tuple[object, int]:
        """The ``CheckpointWatcher`` loader: delta path for manifests,
        byte-identical fallback (and cache reset) for npz/``.ckpt``."""
        if not is_manifest(path):
            from pytorch_distributed_mnist_tpu.serve.engine import (
                load_params_for_serving,
            )

            self._hashes, self._values = {}, {}
            self.total["full_loads"] += 1
            return load_params_for_serving(path, template_state)
        manifest = read_manifest(path)  # torn -> JSONDecodeError
        stats = _zeroed()
        records = {rec["name"]: rec for rec in manifest["leaves"]}
        import jax

        flat, treedef = jax.tree_util.tree_flatten_with_path(
            template_state.params)
        leaves, hashes = [], {}
        for kpath, tmpl in flat:
            name = PARAMS_PREFIX + jax.tree_util.keystr(kpath)
            rec = records.get(name)
            if rec is None:
                raise ValueError(
                    f"{path}: no leaf {name!r} in manifest — "
                    f"model/checkpoint mismatch")
            key = tuple(rec["chunks"])
            hashes[name] = key
            if self._hashes.get(name) == key and name in self._values:
                leaves.append(self._values[name])
                stats["clean_leaves"] += 1
                continue
            for dg in rec["chunks"]:
                self._obtain(dg, stats)
            from pytorch_distributed_mnist_tpu.distrib.cas import (
                assemble_leaf,
            )

            arr = assemble_leaf(rec, self.store)
            if tuple(arr.shape) != tuple(np.shape(tmpl)):
                raise ValueError(
                    f"{path}: leaf {name} shape {arr.shape} != expected "
                    f"{tuple(np.shape(tmpl))}")
            stats["bytes_local"] += arr.nbytes
            if hasattr(tmpl, "dtype"):
                arr = arr.astype(tmpl.dtype, copy=False)
            leaves.append(arr)
            stats["dirty_leaves"] += 1
        params = jax.tree_util.tree_unflatten(treedef, leaves)
        if self._precision is not None and not self._precision.identity:
            # Quantize HERE so clean leaves keep their previous
            # QuantLeaf objects (idempotent passthrough) and only dirty
            # leaves pay the quantization — then cache per leaf for the
            # next manifest's diff.
            params = self._precision.quantize(params, workers=self._workers)
        out_flat, _ = jax.tree_util.tree_flatten_with_path(
            params, is_leaf=_is_precision_leaf)
        values = {PARAMS_PREFIX + jax.tree_util.keystr(p): v
                  for p, v in out_flat}
        self._hashes, self._values = hashes, values
        stats["delta_loads"] = 1
        self.last = stats
        for k, v in stats.items():
            self.total[k] += v
        print(f"delta fetch: {path!r} {stats['dirty_leaves']} dirty / "
              f"{stats['clean_leaves']} clean leaves, "
              f"{stats['chunks_fetched']} chunks fetched "
              f"({stats['bytes_peer']}B peer, {stats['bytes_source']}B "
              f"source)", flush=True)
        return params, int(manifest["epoch"]) - 1


def _is_precision_leaf(x) -> bool:
    from pytorch_distributed_mnist_tpu.serve.programs import QuantLeaf

    return isinstance(x, QuantLeaf)
