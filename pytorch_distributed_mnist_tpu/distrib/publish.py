"""Trainer-side delta publish: chunks absent from the store + manifest.

``publish_arrays`` is the core: given host arrays in flat leaf order it
stores only the chunks the store does not already hold (adjacent
training epochs share most bytes, so this is the O(changed bytes)
step), then publishes the manifest tmp+rename — the one atomic instant
— then prunes manifests by the SAME window rule npz/sharded layouts
use (``prune_checkpoints``; the shared ``_epoch_checkpoints`` pattern
now matches ``.manifest`` too) and extends that window to chunks:
``gc_chunks`` deletes only chunks referenced by NO manifest still on
disk. A chunk referenced by any manifest inside the keep-last window —
including one a watcher is mid-fetch on — therefore survives exactly
as long as the manifest does, the PR 3 ordering guarantee carried down
one level.

``publish_from_checkpoint`` converts an already-published npz or
sharded ``.ckpt`` checkpoint into a manifest in place (or into another
directory) — the router's ``/rollout`` path, so a fleet deploy ships a
few-KB manifest instead of copying the whole file per backend.
"""

from __future__ import annotations

import glob
import os
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from pytorch_distributed_mnist_tpu.distrib.cas import (
    ChunkStore,
    MANIFEST_SUFFIX,
    build_manifest,
    manifest_digests,
    read_manifest,
    write_manifest,
)


def gc_chunks(directory: str) -> int:
    """Delete chunks referenced by no manifest in ``directory``; returns
    bytes freed. The referenced set is computed from every ``*.manifest``
    still on disk — per-epoch manifests the prune window kept AND the
    ``model_best`` copy — so the window rule protects chunks exactly as
    long as it protects the manifest referencing them. Quarantined
    manifests (``.corrupt`` suffix) are unreadable provenance, not live
    references; their chunks are collectable once no live manifest
    shares them."""
    referenced: set = set()
    for path in glob.glob(os.path.join(directory, f"*{MANIFEST_SUFFIX}")):
        try:
            referenced |= manifest_digests(read_manifest(path))
        except Exception:  # noqa: BLE001 - a torn manifest pins nothing
            continue
    return ChunkStore(directory).gc(referenced)


def publish_arrays(
    named: Sequence[Tuple[str, np.ndarray]],
    *,
    epoch: int,
    best_acc: float,
    directory: str,
    chunk_mb: float = 4.0,
    is_best: bool = False,
    keep_last: int = 0,
    world: Optional[Dict[str, int]] = None,
    parallel_layout: Optional[Dict[str, Any]] = None,
) -> str:
    """Chunk + store + manifest publish; returns the manifest path.

    Ordering is the atomicity argument: every referenced chunk is on
    disk (write-once, tmp+rename each) BEFORE the manifest rename makes
    the epoch visible, so a watcher that resolves the manifest can
    assemble it; a crash between chunk writes and the rename leaves
    only unreferenced chunks, collected by the next publish's GC."""
    from pytorch_distributed_mnist_tpu.train.checkpoint import (
        prune_checkpoints,
    )

    store = ChunkStore(directory)
    manifest, stream = build_manifest(
        named, epoch=epoch, best_acc=best_acc, chunk_mb=chunk_mb,
        world=world, parallel_layout=parallel_layout)
    written = 0
    for digest, data in stream:
        if store.put(digest, data):
            written += len(data)
    path = write_manifest(manifest, directory, epoch)
    total = sum(rec_len for _, data in stream for rec_len in (len(data),))
    print(f"delta publish: epoch {epoch} -> {path} "
          f"({written}/{total} chunk bytes new)", flush=True)
    if is_best:
        best = os.path.join(directory, f"model_best{MANIFEST_SUFFIX}")
        tmp = best + ".tmp"
        import shutil

        shutil.copyfile(path, tmp)
        os.replace(tmp, best)
    prune_checkpoints(directory, keep_last)
    if keep_last > 0:
        gc_chunks(directory)
    return path


def publish_state(
    state,
    *,
    epoch: int,
    best_acc: float,
    directory: str,
    chunk_mb: float = 4.0,
    is_best: bool = False,
    keep_last: int = 0,
    process_index: Optional[int] = None,
    parallel_layout: Optional[Dict[str, Any]] = None,
) -> Optional[str]:
    """Delta-publish a live train state (the ``--publish delta`` path).

    Process-0-only, like the npz layout it replaces: every leaf must be
    fully addressable or replicated from this process. A genuinely
    cross-host-sharded state (multi-host TP/EP/ZeRO) has no single-host
    byte stream to chunk — publish the sharded layout and convert with
    ``publish_from_checkpoint`` instead; that mismatch aborts loudly
    here rather than silently chunking one host's shard view."""
    import jax

    from pytorch_distributed_mnist_tpu.train.checkpoint import (
        _leaves_with_names,
        _npz_saveable,
        _state_tree,
        _world_stamp,
    )

    named = _leaves_with_names(_state_tree(state))
    bad = [k for k, v in named if not _npz_saveable(v)]
    if bad:
        raise ValueError(
            f"--publish delta requires fully-addressable (or replicated) "
            f"leaves; {bad[:3]} span non-addressable devices — save the "
            f"sharded layout and convert via publish_from_checkpoint")
    pid = jax.process_index() if process_index is None else process_index
    if pid != 0:
        return None
    host = [(k, np.asarray(v)) for k, v in named]
    return publish_arrays(
        host, epoch=epoch, best_acc=best_acc, directory=directory,
        chunk_mb=chunk_mb, is_best=is_best, keep_last=keep_last,
        world=_world_stamp(), parallel_layout=parallel_layout)


def publish_from_checkpoint(
    path: str,
    directory: Optional[str] = None,
    *,
    chunk_mb: float = 4.0,
    keep_last: int = 0,
) -> str:
    """Convert a published npz/``.ckpt`` checkpoint (or re-publish an
    existing manifest) into a manifest in ``directory`` (default: the
    checkpoint's own directory). Epoch, best_acc, world, and
    parallel_layout carry over from the source meta, so the layout gate
    and epoch ordering see the converted manifest exactly as they saw
    the source file."""
    from pytorch_distributed_mnist_tpu.train.checkpoint import (
        read_checkpoint_arrays,
    )

    meta, arrays = read_checkpoint_arrays(path)
    directory = directory or os.path.dirname(os.path.abspath(path))
    return publish_arrays(
        list(zip(meta["leaf_names"], arrays)),
        epoch=int(meta["epoch"]) - 1,
        best_acc=float(meta.get("best_acc", 0.0)),
        directory=directory, chunk_mb=chunk_mb, keep_last=keep_last,
        world=meta.get("world"),
        parallel_layout=meta.get("parallel_layout"))
