"""Delta weight distribution: content-addressed checkpoints.

The distribution subsystem (DESIGN.md §7m): checkpoints become a
MANIFEST (the atomic publish unit, a small JSON file) plus
content-addressed chunks in a write-once store, so publishing epoch
N+1 after epoch N moves only the chunks that changed — O(changed
bytes), not O(replicas x full file). Three modules:

- ``cas.py``    — the chunk store + deterministic chunk planning +
                  manifest read/write (the format layer);
- ``publish.py``— the trainer side: delta publish wired into
                  ``train/checkpoint.py`` (``--publish delta``), chunk
                  GC extending the prune window rule;
- ``fetch.py``  — the serve side: the ``CheckpointWatcher`` loader
                  that diffs a manifest against the local inventory,
                  fetches only missing chunks (peer backends first,
                  source dir fallback), patches leaves, and
                  re-quantizes only dirtied ones.
"""

from pytorch_distributed_mnist_tpu.distrib.cas import (  # noqa: F401
    ChunkStore,
    MANIFEST_SUFFIX,
    chunk_leaf,
    load_manifest_arrays,
    manifest_digests,
    read_manifest,
    write_manifest,
)
from pytorch_distributed_mnist_tpu.distrib.fetch import (  # noqa: F401
    DeltaFetcher,
)
from pytorch_distributed_mnist_tpu.distrib.publish import (  # noqa: F401
    gc_chunks,
    publish_arrays,
    publish_from_checkpoint,
)
