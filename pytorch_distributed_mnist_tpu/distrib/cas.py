"""Content-addressed chunk store + manifest format.

A checkpoint leaf's canonical bytes (C-order ``tobytes()``) are split
into chunks at FIXED byte offsets of a ``--chunk-mb`` budget, each chunk
named by its sha256 and written once into ``<dir>/chunks/``. The leaf
traversal order reuses the PR 7 ``bucket_plan`` packing discipline
(largest-first, flat-index tie-break) — the same deterministic ordering
the ZeRO buckets pin — so for a model of fixed shapes the chunk
boundaries, the traversal, and therefore every UNCHANGED leaf's chunk
list are identical across epochs. That stability is what makes a delta
publish a set-difference: chunks already in the store are never
rewritten (write-once), and a fetcher's diff of manifest-vs-inventory
is exact.

The MANIFEST is the atomic publish unit: ``checkpoint_{e}.manifest``,
a JSON file carrying the same meta the npz/sharded layouts stamp
(``epoch`` as ``epoch+1``, ``best_acc``, ``leaf_names``, ``world``,
``parallel_layout``) plus per-leaf ``{shape, dtype, chunks, lengths}``.
It is written tmp+rename AFTER every chunk it references is on disk,
so a reader that can parse a manifest can (absent external deletion)
assemble it. A torn manifest is a ``json.JSONDecodeError`` — already
classified content-level damage by ``is_corrupt_checkpoint_error``, so
resume quarantines it and the serve watcher permanent-skips it exactly
like a torn npz today. A MISSING CHUNK raises a ValueError (message
``missing chunk``, deliberately distinct from the sharded layout's
``missing shards`` stale-NFS case): absence-level, permanent for that
publish at the watcher, loud abort at resume.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from pytorch_distributed_mnist_tpu.parallel.zero_overlap import bucket_plan

MANIFEST_SUFFIX = ".manifest"
CHUNK_DIR = "chunks"
MANIFEST_VERSION = 1

_DIGEST_RE = re.compile(r"[0-9a-f]{64}")


def is_manifest(path: str) -> bool:
    return path.endswith(MANIFEST_SUFFIX)


def _digest(data) -> str:
    return hashlib.sha256(data).hexdigest()


class ChunkStore:
    """Write-once sha256-named chunk files under ``<directory>/chunks/``.

    ``directory`` is the CHECKPOINT directory — chunks live beside the
    manifests that reference them, so the prune window and the chunk GC
    see one consistent namespace. ``put`` verifies content against the
    digest (a fetcher installs peer-supplied bytes through here, so a
    corrupt peer can never poison the store) and is tmp+rename atomic;
    an already-present digest is never rewritten.
    """

    def __init__(self, directory: str) -> None:
        self.directory = directory
        self.root = os.path.join(directory, CHUNK_DIR)

    def path(self, digest: str) -> str:
        return os.path.join(self.root, digest)

    def has(self, digest: str) -> bool:
        return os.path.isfile(self.path(digest))

    def put(self, digest: str, data: bytes) -> bool:
        """Store ``data`` under ``digest``; returns True when bytes were
        written (False: already present — the write-once fast path that
        makes adjacent-epoch publishes cheap)."""
        if self.has(digest):
            return False
        if _digest(data) != digest:
            raise ValueError(
                f"chunk content does not match its digest {digest} — "
                f"refusing to store corrupt bytes")
        os.makedirs(self.root, exist_ok=True)
        path = self.path(digest)
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
        return True

    def get(self, digest: str) -> bytes:
        path = self.path(digest)
        try:
            with open(path, "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise ValueError(
                f"missing chunk {digest} in {self.root} — the manifest "
                f"references a chunk this store does not hold") from None

    def digests(self) -> set:
        if not os.path.isdir(self.root):
            return set()
        return {name for name in os.listdir(self.root)
                if _DIGEST_RE.fullmatch(name)}

    def gc(self, referenced: set) -> int:
        """Delete chunk files not in ``referenced``; returns bytes freed."""
        freed = 0
        for digest in self.digests() - set(referenced):
            path = self.path(digest)
            try:
                freed += os.path.getsize(path)
                os.remove(path)
            except OSError:
                pass  # raced by a concurrent publish's put: keep it
        return freed


def chunk_budget_bytes(chunk_mb: float) -> int:
    if chunk_mb <= 0:
        raise ValueError(f"chunk_mb must be > 0, got {chunk_mb}")
    return int(chunk_mb * (1 << 20))


def chunk_leaf(data: bytes, budget: int) -> Tuple[List[str], List[int]]:
    """Split a leaf's canonical bytes at fixed ``budget`` offsets.

    Boundaries depend only on the leaf's byte length and the budget —
    never on content — so an unchanged leaf reproduces the identical
    (digests, lengths) across epochs and a changed leaf dirties only
    the chunks whose bytes actually differ."""
    digests, lengths = [], []
    for off in range(0, max(len(data), 1), budget):
        piece = data[off:off + budget]
        digests.append(_digest(piece))
        lengths.append(len(piece))
    return digests, lengths


def leaf_bytes(arr: np.ndarray) -> bytes:
    """The leaf's canonical chunk-stream representation: C-order raw
    bytes of the host array (dtype preserved — the manifest records it,
    so assembly is a ``frombuffer`` + ``reshape``, no re-encode)."""
    return np.ascontiguousarray(arr).tobytes()


def plan_order(arrays: Sequence[np.ndarray], chunk_mb: float) -> List[int]:
    """The deterministic leaf traversal: ``bucket_plan``'s size-ordered
    packing (largest-first, flat-index tie-break) flattened back to one
    index sequence. Reusing the ZeRO bucket planner — rather than a
    second ad-hoc sort — is what the chunk-boundary stability test pins:
    the distribution plane and the communication plane order leaves by
    the SAME rule, so neither can drift without the other noticing."""
    return [i for bucket in bucket_plan(arrays, chunk_mb) for i in bucket]


def build_manifest(
    named: Sequence[Tuple[str, np.ndarray]],
    *,
    epoch: int,
    best_acc: float,
    chunk_mb: float,
    world: Optional[Dict[str, int]] = None,
    parallel_layout: Optional[Dict[str, Any]] = None,
) -> Tuple[Dict[str, Any], List[Tuple[str, bytes]]]:
    """Chunk every leaf; returns ``(manifest, chunk_stream)`` where
    ``chunk_stream`` is ``[(digest, bytes), ...]`` in the deterministic
    plan order (duplicates removed — identical leaves share chunks).

    ``named`` is ``[(leaf_name, host_array), ...]`` in flat (leaf_names)
    order; the manifest's ``leaves`` list keeps that order so assembly
    mirrors the npz layout's ``leaf_i`` indexing."""
    budget = chunk_budget_bytes(chunk_mb)
    arrays = [np.asarray(v) for _, v in named]
    records: List[Dict[str, Any]] = []
    by_digest: Dict[str, bytes] = {}
    per_leaf: List[List[str]] = []
    for name, arr in zip((k for k, _ in named), arrays):
        data = leaf_bytes(arr)
        digests, lengths = chunk_leaf(data, budget)
        per_leaf.append(digests)
        for j, (dg, ln) in enumerate(zip(digests, lengths)):
            if dg not in by_digest:
                by_digest[dg] = data[j * budget:j * budget + ln]
        records.append({
            "name": name,
            "shape": list(arr.shape),
            "dtype": arr.dtype.name,
            "chunks": digests,
            "lengths": lengths,
        })
    manifest = {
        "epoch": epoch + 1,
        "best_acc": float(best_acc),
        "leaf_names": [k for k, _ in named],
        "format_version": MANIFEST_VERSION,
        "chunk_mb": float(chunk_mb),
        "leaves": records,
    }
    if world is not None:
        manifest["world"] = dict(world)
    if parallel_layout is not None:
        manifest["parallel_layout"] = dict(parallel_layout)
    # Chunk write order follows the plan: leaves largest-first, each
    # leaf's chunks in offset order, each distinct digest once.
    stream: List[Tuple[str, bytes]] = []
    emitted = set()
    for i in plan_order(arrays, chunk_mb):
        for dg in per_leaf[i]:
            if dg not in emitted:
                emitted.add(dg)
                stream.append((dg, by_digest[dg]))
    return manifest, stream


def write_manifest(manifest: Dict[str, Any], directory: str,
                   epoch: int) -> str:
    """Atomic manifest publish: tmp + rename, same as the npz writer.
    Callers must have stored every referenced chunk FIRST — the rename
    is the instant the epoch becomes visible to watchers."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"checkpoint_{epoch}{MANIFEST_SUFFIX}")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f)
    os.replace(tmp, path)
    return path


def read_manifest(path: str) -> Dict[str, Any]:
    """Parse a manifest; a torn/truncated one raises ``JSONDecodeError``
    — content-level damage under ``is_corrupt_checkpoint_error``, so the
    resume path quarantines it and the watcher permanent-skips it."""
    with open(path) as f:
        return json.load(f)


def manifest_digests(manifest: Dict[str, Any]) -> set:
    return {dg for rec in manifest["leaves"] for dg in rec["chunks"]}


def assemble_leaf(rec: Dict[str, Any], store: ChunkStore) -> np.ndarray:
    """One leaf from its ordered chunk list; a missing chunk raises the
    absence-level ValueError documented on ``ChunkStore.get``."""
    data = b"".join(store.get(dg) for dg in rec["chunks"])
    arr = np.frombuffer(data, dtype=np.dtype(rec["dtype"]))
    return arr.reshape(rec["shape"])


def load_manifest_arrays(
    path: str, store: Optional[ChunkStore] = None,
) -> Tuple[Dict[str, Any], List[np.ndarray]]:
    """Whole-file assembly of a manifest: ``(manifest, arrays)`` in
    leaf_names order — the ``load_checkpoint`` branch, so resume and
    serve boot read manifests through the exact same
    restore-onto-template path as npz files."""
    manifest = read_manifest(path)
    if store is None:
        store = ChunkStore(os.path.dirname(os.path.abspath(path)))
    return manifest, [assemble_leaf(rec, store) for rec in manifest["leaves"]]
