"""Elastic worlds: survive a host loss by shrinking, not exiting.

The supervision layer (``runtime/supervision.py``) turns a dead host
into a clean agreed exit: every survivor unwinds with
``PeerFailure(host, phase, reason)`` instead of hanging in a
timeout-less collective. This module is the layer ABOVE that exit —
ROADMAP item 5: on a host loss the *job continues*. Survivors agree the
shrunk membership, the world is rebuilt at the smaller size, state is
re-sharded from the last *published* checkpoint (cross-world checkpoint
resharding, ``train/checkpoint.py``), and training resumes — all
without operator action.

Why re-exec instead of in-place rebuild: ``jax.distributed`` membership
is fixed at initialize time — the coordination service has no
remove-member operation, survivors cannot re-initialize a smaller world
inside a process whose backend (and, on CPU pods, whose gloo transport)
is already bound to the dead one, and the dead host may *be* the
coordinator. So the contract "training resumes without human
intervention" is met by **supervised re-exec**: an elastic supervisor
process owns the worker processes, and each failed *generation* is
replaced by a smaller one resumed from the last published checkpoint.
(This is also the only shape that generalizes to real pods, where the
restart actor is the cluster manager; ``supervise`` below is that actor
for the local ``--spawn`` simulation and the chaos harness.)

The protocol, per generation ``g`` with members ``[h0..h{W-1}]`` (stable
host ids; rank within the generation is the index):

1. **Detect** — any failure inside the generation takes the supervised
   exit paths PR 2 built: poison pill, watchdog, or transport error,
   each ending every *surviving* rank in ``PeerFailure`` with the dead
   hosts attributed.
2. **Agree membership** — each survivor, while unwinding, writes a
   **survivor record** (``write_survivor_record``, called from
   ``cli.run``'s supervised scope): its rank, its host id, and the dead
   set its ``PeerFailure`` named. The dead set came off the supervision
   record channel — every survivor decoded the SAME pill / the same
   silent-peer report — so the records are the membership agreement,
   serialized to the rendezvous directory where the supervisor (which
   outlives the broken world) can read it. A rank that exits without a
   record is, by that fact, not a survivor.
3. **Rebuild** — the supervisor collects exits and records under a
   deadline (a second failure *during* the shrink — a survivor that
   dies or stalls before its record lands — just makes the next world
   smaller; a straggler is killed at the deadline, never waited on
   forever), plans the next world (``plan_next_world``, pure and
   unit-tested), enforces the ``--min-world`` floor, and re-execs the
   survivors as ranks ``0..W'-1`` of generation ``g+1`` on a fresh
   coordinator port.
4. **Reshard + resume** — generation ``g+1`` runs with
   ``--resume auto``: resolution finds the last *published* checkpoint
   (unpublished ``.tmp`` dirs are invisible; a corrupt latest is
   quarantined with fallback), and ``load_checkpoint`` re-shards it
   onto the smaller world whatever layout it was saved in (npz or
   sharded directory; plain DP, zero1, zero3) — the cross-world
   contract ``tests/test_reshard.py`` pins. The rebuilt world records a
   ``world_shrunk`` failure event (old/new membership) into the run
   summary and the ``--metrics-file`` JSONL.

What shrinking cannot promise: the global ``--batch-size`` must still
divide the shrunk world's device count (a 4-host world at batch 256
shrinks to 3 hosts only if 256 splits 3 ways — it does not; choose
worlds and batches with divisible fallbacks), and a second failure can
shrink the world below ``--min-world``, which exits loudly
(``EXIT_FLOOR``) rather than training on a world the operator ruled
out. A failure with NO survivors (or one that implicates nobody — a
symmetric abort like a dataset vote rejection) is not a shrink event
and propagates as the failure it is.

Fault points: ``elastic_rebuild`` fires in the survivor-record path, so
the chaos harness can kill or stall a survivor *mid-shrink*
(``tools/chaos.py --elastic --fault
"resume:2:kill,elastic_rebuild:1:stall"``) and prove the
second-failure-during-rebuild story end to end.

**Growing the world** (ROADMAP item 3: topology change as a routine
event, both directions): a returned or replacement host announces
itself by writing a **join record** (``announce_join``) into the same
rendezvous directory the survivor votes live in. Join records are
admitted at *generation boundaries* — the only points where the world
is already being rebuilt and a membership change costs nothing extra:

- after any failure-triggered rebuild, unconditionally (a replacement
  arriving mid-shrink rides the rebuild that is happening anyway — a
  simultaneous loss-plus-replacement re-launches at the same size);
- at an **epoch-boundary grow rendezvous** when the supervisor runs
  with ``--elastic-grow``: rank 0 lists pending join records after each
  epoch's checkpoint publish, the observation is agreed over the one
  supervision record channel (symmetric — every rank runs the same
  collective), and when joiners are pending every rank writes a YIELD
  record and exits with the distinct ``EXIT_GROW`` code. To the
  supervisor a yielded generation is a planned regroup, not a failure:
  yielders are survivors by record, joiners are appended (stable new
  host ids, capped by ``--max-world``), and generation ``g+1`` re-execs
  as ranks ``0..W'-1`` with ``W' > W``.

The resume bit is the part that was already paid for: ``--resume auto``
resolves the last published checkpoint and ``load_checkpoint``'s
(W, W') reshard matrix covers W' > W exactly as it covers W' < W
(``tests/test_reshard.py``), so the grown world's state is bit-identical
to a fresh large-world shard of the same arrays. The rebuilt generation
records a ``world_grown`` event (mirror of ``world_shrunk``) into the
run summary and the metrics JSONL.

What a joiner cannot do: join MID-collective. A generation's membership
is fixed at ``jax.distributed`` initialize time, so a joiner is only
ever admitted between generations — it waits (its record pending) until
the next boundary. Stale join records — a host that is already a member
(e.g. its own pre-loss record resurfacing) — are consumed and ignored,
never double-admitted; records beyond the ``--max-world`` cap stay
pending for a later boundary.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from pytorch_distributed_mnist_tpu.parallel.launcher import (
    _child_env,
    free_port,
    strip_flags,
    strip_spawn_flag,
)
from pytorch_distributed_mnist_tpu.runtime import supervision

# Environment contract between the supervisor and its worker processes.
# Workers never need a flag: presence of the rendezvous DIR enables the
# survivor-record path, and MEMBERS/GEN/PREV carry the membership the
# worker reports in records and the world_shrunk event.
DIR_ENV = "TPUMNIST_ELASTIC_DIR"
GEN_ENV = "TPUMNIST_ELASTIC_GEN"
MEMBERS_ENV = "TPUMNIST_ELASTIC_MEMBERS"
PREV_ENV = "TPUMNIST_ELASTIC_PREV"
# Set ("1") by a supervisor running with --elastic-grow: workers then
# run the epoch-boundary grow rendezvous (maybe_grow_rendezvous).
GROW_ENV = "TPUMNIST_ELASTIC_GROW"
# The supervisor's --max-world cap, mirrored to workers so a world
# already AT the cap skips the rendezvous entirely: without this, a
# join record the supervisor can only defer would re-trigger a yield
# (full teardown + re-exec) at EVERY epoch boundary.
MAX_WORLD_ENV = "TPUMNIST_ELASTIC_MAX_WORLD"

# Supervisor exit code when survivors would form a world below
# --min-world: distinct from worker failure codes (1, watchdog 75,
# signal 128+N) so an operator-side restart policy can tell "the job
# shrank past the floor you set" from "the job failed".
EXIT_FLOOR = 78


def generation() -> int:
    """This worker's elastic generation: 0 for the first launch (and for
    every non-elastic run), >= 1 inside a rebuilt world. Callers use it
    to tell "the operator asked for this topology" (reject bad flags
    loudly) from "the supervisor rebuilt us into it" (degrade
    gracefully — e.g. cli.py's flat-mesh fallback when a slice loss
    leaves a world the configured DCN slice count no longer divides)."""
    return int(os.environ.get(GEN_ENV, "0") or 0)

# Worker exit code for the planned grow rendezvous: every rank of a
# generation that agreed pending joiners exist yields with this code
# (plus a YIELD record — either alone proves the rank is healthy), so
# the supervisor can tell "the world paused to grow" from every failure
# shape. Distinct from 0 (trained to completion), 75 (watchdog hard
# exit), and 78 (the supervisor's floor).
EXIT_GROW = 76

# Substrings that mark an exception as transport-shaped: the peer died
# while this host was inside a DEVICE program (a step's psum) or another
# non-agreement collective, so the failure never passed through
# allgather_records' transport classifier and arrives as a raw runtime
# error. Matched case-insensitively against repr(exc). Best-effort by
# design: a miss means this rank writes no record and is treated as
# dead — strictly a smaller next world, never a hang.
_TRANSPORT_MARKERS = (
    "gloo",
    "connection closed",
    "connection reset",
    "connection refused",
    "broken pipe",
    "peer closed",
    "socket closed",
    "transport",
    "deadline exceeded",
    "heartbeat",
    "coordination service",
)


def is_transport_suspect(error: BaseException) -> bool:
    """True when ``error`` reads like the transport-level shadow of a
    peer death (see ``_TRANSPORT_MARKERS``). Used only to widen the
    survivor-record gate beyond ``PeerFailure``; never to suppress a
    real failure."""
    text = repr(error).lower()
    return any(marker in text for marker in _TRANSPORT_MARKERS)


def _members_from_env() -> List[int]:
    raw = os.environ.get(MEMBERS_ENV, "")
    return [int(tok) for tok in raw.split(",") if tok.strip() != ""]


def record_path(directory: str, generation: int, rank: int) -> str:
    return os.path.join(directory,
                        f"survivor_g{generation:03d}_r{rank:05d}.json")


def join_path(directory: str, host: int) -> str:
    return os.path.join(directory, f"join_h{host:05d}.json")


def announce_join(directory: str, host: int) -> str:
    """The joiner's announcement: a returned or replacement host writes
    one join record into the rendezvous directory and waits to be
    admitted at the next generation boundary (a failure rebuild, or an
    epoch-boundary grow rendezvous under ``--elastic-grow``). ``host``
    is the stable host id the new member will carry; a RETURNED host
    reuses its old id, a replacement picks an unused one. Atomic
    tmp+replace like the survivor votes, so the supervisor never reads
    a torn announcement. Returns the record path.

    This is the whole joiner-side protocol on purpose: admission, rank
    assignment, and resume all belong to the supervisor and the rebuilt
    generation — a joiner cannot enter a *running* world (jax.distributed
    membership is fixed at initialize time), so anything beyond
    "announce and wait" would be a lie about what a mid-collective
    joiner can do.
    """
    record = {"host": int(host), "wall": round(time.time(), 3)}
    path = join_path(directory, int(host))
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f)
    os.replace(tmp, path)
    return path


def pending_joins(directory: str) -> List[Tuple[int, str]]:
    """All parseable join records in the rendezvous dir, sorted by host
    id: ``[(host, path), ...]``. Malformed records are warned about and
    skipped (never admitted, never deleted — the evidence stays for the
    operator); missing/unreadable dirs read as no joiners."""
    out: List[Tuple[int, str]] = []
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("join_h") and name.endswith(".json")):
            continue
        path = os.path.join(directory, name)
        try:
            with open(path) as f:
                host = int(json.load(f)["host"])
        except (OSError, ValueError, KeyError, TypeError,
                json.JSONDecodeError) as exc:
            print(f"WARNING: ignoring malformed join record {path!r} "
                  f"({exc!r})", file=sys.stderr, flush=True)
            continue
        out.append((host, path))
    return sorted(out)


def plan_grow(
    members: Sequence[int],
    join_hosts: Sequence[int],
    max_world: int = 0,
) -> Tuple[List[int], List[int], List[int]]:
    """The grow half of the membership decision, as a pure function:
    ``(new_members, admitted, stale)``.

    Joiners are appended to the surviving members in host-id order
    (survivor ranks stay a prefix: the grown world's rank 0 is the old
    world's rank 0, which keeps log-follows-rank-0 stable across
    regrows). ``stale`` joiners — already members — are ignored (and the
    caller consumes their records so a host's pre-loss announcement can
    never readmit it after a later death). ``max_world`` (0 = unbounded)
    caps the TOTAL world size; joiners beyond the cap are neither
    admitted nor stale — they stay pending for a later boundary.
    """
    members = list(members)
    admitted: List[int] = []
    stale: List[int] = []
    for host in sorted(set(int(h) for h in join_hosts)):
        if host in members:
            stale.append(host)
            continue
        if max_world and len(members) + len(admitted) >= max_world:
            continue  # deferred: stays pending for a later boundary
        admitted.append(host)
    return members + admitted, admitted, stale


def write_survivor_record(error: BaseException) -> Optional[str]:
    """Worker-side membership vote: serialize this host's survival (and
    the dead set its failure named) for the supervisor; returns the
    record path, or None when this process is not an elastic worker or
    ``error`` does not qualify.

    Called from ``cli.run``'s supervised unwind, before the poison-pill
    delivery and exit escalation (the record is local sub-second file
    I/O; a pill attempt against dead transport can block for its whole
    bounded timeout, and the vote must not wait behind it). Qualifying
    errors: ``PeerFailure`` (the
    supervision channel attributed the dead hosts — ``dead_ranks`` is
    that attribution, verbatim) and transport-shaped runtime errors
    (a peer died under a device collective; dead set unknown, the
    supervisor infers it from who else exited recordless). Anything
    else — a genuine host-local error, an agreed symmetric exit,
    KeyboardInterrupt — means this host is failing, not surviving, and
    must not vote itself back into the next world.

    Best-effort on purpose: a record-write failure is reported and
    swallowed (this code runs on an unwind path and must never mask the
    run's own exception); the supervisor then counts this rank dead,
    which only shrinks the next world further.
    """
    directory = os.environ.get(DIR_ENV, "")
    if not directory:
        return None
    if isinstance(error, KeyboardInterrupt):
        return None
    peer = isinstance(error, supervision.PeerFailure)
    if not peer and not is_transport_suspect(error):
        return None
    # Capture the FAILURE's phase before entering the membership phase:
    # a transport-shaped error has no .phase of its own, and reading
    # current_phase() after set_phase below would stamp every such
    # record (and the supervisor's "lost in phase(s)" line) with
    # 'membership' instead of where the world actually died.
    failure_phase = getattr(error, "phase", None) \
        or supervision.current_phase()
    supervision.set_phase("membership")
    # The mid-rebuild fault point: a kill here is a survivor dying
    # DURING the shrink (no record lands -> the supervisor counts it
    # dead); a stall is a survivor hanging mid-shrink (killed at the
    # supervisor's settle deadline). Either way the rebuild completes.
    supervision.maybe_fault("elastic_rebuild")
    members = _members_from_env()
    gen = generation()
    rank = supervision.process_index()
    dead_ranks = sorted(getattr(error, "hosts", []) or []) if peer else []
    record = {
        "generation": gen,
        "rank": rank,
        "host": members[rank] if rank < len(members) else rank,
        "dead_ranks": dead_ranks,
        "dead_hosts": [members[r] for r in dead_ranks
                       if r < len(members)] if members else dead_ranks,
        "phase": failure_phase,
        "reason": repr(error)[:500],
        "wall": round(time.time(), 3),
    }
    path = record_path(directory, gen, rank)
    try:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(record, f)
        os.replace(tmp, path)  # atomic: the supervisor never reads a torn vote
    except Exception as exc:  # noqa: BLE001 - unwind path: never mask `error`
        print(f"WARNING: elastic survivor record {path} could not be "
              f"written ({exc!r}); the supervisor will count this rank "
              f"dead and shrink without it", file=sys.stderr, flush=True)
        return None
    print(f"process {rank}: survivor record written ({path}); dead "
          f"rank(s) {dead_ranks or 'unknown'} — awaiting rebuild into "
          f"the shrunk world", file=sys.stderr, flush=True)
    return path


def write_yield_record(join_hosts: Sequence[int]) -> Optional[str]:
    """Worker-side grow vote: serialize this rank's healthy yield at a
    grow rendezvous (the grow twin of ``write_survivor_record``, written
    on the agreed EXIT_GROW path rather than an unwind). A yield record
    is proof of a live, healthy rank — ``plan_next_world`` counts it a
    survivor — with ``yield: true`` telling the supervisor the
    generation paused to grow rather than failed. Best-effort like the
    survivor vote: on a write failure the rank still exits EXIT_GROW,
    which the supervisor maps to survivor on its own."""
    directory = os.environ.get(DIR_ENV, "")
    if not directory:
        return None
    members = _members_from_env()
    gen = generation()
    rank = supervision.process_index()
    record = {
        "generation": gen,
        "rank": rank,
        "host": members[rank] if rank < len(members) else rank,
        "yield": True,
        "join_hosts": sorted(int(h) for h in join_hosts),
        "dead_ranks": [],
        "dead_hosts": [],
        "phase": "grow_check",
        "reason": f"grow rendezvous: pending joiner(s) "
                  f"{sorted(int(h) for h in join_hosts)}",
        "wall": round(time.time(), 3),
    }
    path = record_path(directory, gen, rank)
    try:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(record, f)
        os.replace(tmp, path)
    except Exception as exc:  # noqa: BLE001 - EXIT_GROW still proves the yield
        print(f"WARNING: elastic yield record {path} could not be "
              f"written ({exc!r}); the EXIT_GROW code alone carries the "
              f"vote", file=sys.stderr, flush=True)
        return None
    return path


def maybe_grow_rendezvous() -> Optional[List[int]]:
    """Worker-side, at each epoch boundary (after the checkpoint save):
    agree whether join records are pending. Returns the agreed joiner
    host list when the generation should yield for a grow, ``None``
    otherwise (not an elastic-grow worker, world at ``--max-world``, or
    nothing pending). The caller (``cli``'s epoch loop) breaks out of
    training CLEANLY on a non-None return and only then calls
    :func:`yield_for_grow` — ordering that matters under
    ``--async-checkpoint``, where the deferred publish barrier runs on
    the saver's *clean* exit: raising from inside the saver scope would
    DROP the just-saved epoch's unpublished checkpoint and make the
    grown world resume one epoch back.

    Symmetry is the whole design: rank 0 alone lists the rendezvous dir
    (host-local file I/O — per-rank listings could disagree on a shared
    filesystem's attribute cache), but EVERY rank runs the one agreement
    collective, and every rank acts on rank 0's detail — so all ranks
    yield or none do, and the collective count stays aligned.
    """
    directory = os.environ.get(DIR_ENV, "")
    if not directory or os.environ.get(GROW_ENV, "") != "1":
        return None
    members = _members_from_env()
    max_world = int(os.environ.get(MAX_WORLD_ENV, "0") or 0)
    if max_world and len(members) >= max_world:
        # At the cap, nothing can be admitted: yielding would tear the
        # world down for a rendezvous the supervisor could only defer —
        # and the still-pending record would re-trigger it EVERY epoch.
        # (Below the cap a yield always admits at least one joiner:
        # stale member records are filtered right here.)
        return None
    joins: List[int] = []
    if supervision.process_index() == 0:
        joins = [h for h, _ in pending_joins(directory)
                 if h not in set(members)]
    supervision.set_phase("grow_check")
    records = supervision.allgather_records(
        "grow_check", True, ",".join(str(h) for h in joins))
    supervision.raise_if_poisoned(records, "the grow rendezvous")
    detail = records[0].detail
    if not detail:
        return None
    return [int(tok) for tok in detail.split(",") if tok.strip()]


def yield_for_grow(join_hosts: Sequence[int]) -> None:
    """Worker-side, after the epoch loop unwound cleanly (checkpoints —
    including an async saver's deferred publish — all on disk): write
    this rank's YIELD record and exit ``EXIT_GROW``. Always raises.

    The raise is an agreed symmetric exit (marked, never poisoned):
    every rank of the generation reached the same ``grow_check``
    agreement and leaves through here — to the supervisor, EXIT_GROW
    plus yield records is a planned regroup."""
    join_hosts = list(join_hosts)
    write_yield_record(join_hosts)
    print(f"process {supervision.process_index()}: joiner(s) "
          f"{join_hosts} pending — yielding for the grow rendezvous "
          f"(exit {EXIT_GROW}); the supervisor rebuilds the world "
          f"larger and resumes from the last published checkpoint",
          file=sys.stderr, flush=True)
    exc = SystemExit(EXIT_GROW)
    supervision.mark_agreed(exc)  # symmetric: every rank leaves raising this
    raise exc


def note_rebuilt_world() -> None:
    """Worker-side, at run start: record the ``world_shrunk`` /
    ``world_grown`` failure event when this process is the first
    generation after a membership change.

    Called from ``cli._run_body`` after the failure-event log is reset
    and its metrics sink attached, so the old/new membership lands in
    BOTH the run summary's ``failure_events`` block and the
    ``--metrics-file`` JSONL — the one place an operator (or the
    acceptance twins) reads what the world survived. Direction is sized:
    more members than the previous generation is a grow, fewer a
    shrink; a same-size membership CHANGE (a loss whose replacement
    rode the same rebuild) records as ``world_grown`` — a new host
    joined, and the old/new member lists carry the loss. No-op outside
    a rebuilt elastic generation, and for an unchanged relaunch.
    """
    prev = os.environ.get(PREV_ENV, "")
    if not prev or not os.environ.get(DIR_ENV, ""):
        return
    from pytorch_distributed_mnist_tpu.utils.profiling import (
        record_world_grown,
        record_world_shrunk,
    )

    supervision.set_phase("rebuild")
    old_members = [int(t) for t in prev.split(",") if t.strip() != ""]
    new_members = _members_from_env()
    if new_members == old_members:
        return  # a same-membership relaunch changed no topology
    gen = generation()
    if len(new_members) < len(old_members):
        record_world_shrunk(old_members, new_members, gen)
    else:
        record_world_grown(old_members, new_members, gen)


# ---------------------------------------------------------------------------
# Supervisor side
# ---------------------------------------------------------------------------


#: Flags consumed by the supervisor itself; stripped from worker argv
#: (a worker seeing --elastic without --spawn would reject it).
_SUPERVISOR_FLAGS = {"--elastic": 0, "--min-world": 1,
                     "--elastic-grow": 0, "--max-world": 1}


def strip_elastic_flags(argv: Sequence[str]) -> List[str]:
    """Remove supervisor-only flags (``--elastic``, ``--min-world N``,
    ``--elastic-grow``, ``--max-world N``, ``=``-joined forms included)
    from an argv copy."""
    return strip_flags(argv, _SUPERVISOR_FLAGS)


def _strip_resume(argv: Sequence[str]) -> List[str]:
    """Remove any user ``--resume X`` (rebuilt generations always
    resolve the last published checkpoint themselves via ``auto``)."""
    return strip_flags(argv, {"--resume": 1})


def plan_next_world(
    nranks: int,
    returncodes: Sequence[Optional[int]],
    record_ranks: Sequence[int],
) -> Tuple[List[int], List[int]]:
    """The membership decision, as a pure function: ``(survivor_ranks,
    dead_ranks)`` for one failed generation.

    A rank survives iff it *proved* it: exit code 0 (it finished — only
    possible when the failure struck after its last collective), or a
    survivor record on disk (it unwound through the supervised exit and
    voted). Everything else — signal-killed, exited on its own error
    without a record, killed as a straggler at the settle deadline — is
    dead. Record presence outranks the exit code on purpose: a survivor
    whose interpreter teardown hung in the dead world's shutdown
    barrier (killed by the supervisor or hard-exited at code 75) is
    still a healthy host; the record landing is the proof it unwound.
    """
    records = set(record_ranks)
    survivors = [r for r in range(nranks)
                 if r in records or returncodes[r] == 0]
    dead = [r for r in range(nranks) if r not in survivors]
    return survivors, dead


@dataclass
class GenerationResult:
    """One generation's outcome, as the supervisor saw it."""

    generation: int
    members: List[int]
    returncodes: List[Optional[int]]
    records: Dict[int, dict] = field(default_factory=dict)
    stragglers: List[int] = field(default_factory=list)
    log_tails: Dict[int, str] = field(default_factory=dict)

    @property
    def clean(self) -> bool:
        return all(rc == 0 for rc in self.returncodes)

    def first_bad_rc(self) -> int:
        for rc in self.returncodes:
            if rc not in (0, None):
                return rc if rc > 0 else 128 - rc
        return 1


def _say(msg: str) -> None:
    print(f"elastic: {msg}", file=sys.stderr, flush=True)


def _run_generation(
    generation: int,
    members: List[int],
    child_argv: List[str],
    rendezvous_dir: str,
    prev_members: Optional[List[int]],
    settle_timeout: float,
    generation_timeout: float,
    grow: bool = False,
    max_world: int = 0,
) -> GenerationResult:
    """Spawn one generation's worker processes and wait them all out.

    Rank 0 streams to this terminal (the operator watches one log, like
    ``--spawn``); other ranks capture to temp files, tails kept for the
    postmortem of ranks that die. Exit collection is deadline-bounded
    twice over: the whole generation by ``generation_timeout``, and —
    once any rank has exited abnormally — the remaining ranks by
    ``settle_timeout`` from that moment. Ranks still alive past either
    deadline are killed and counted stragglers: a shrink can therefore
    stall for at most ``settle_timeout``, never hang (the
    second-failure-during-rebuild guarantee the mid-rebuild chaos
    scenarios pin).
    """
    nranks = len(members)
    env = _child_env()
    env[DIR_ENV] = rendezvous_dir
    env[GEN_ENV] = str(generation)
    env[MEMBERS_ENV] = ",".join(str(m) for m in members)
    if prev_members is not None:
        env[PREV_ENV] = ",".join(str(m) for m in prev_members)
    else:
        env.pop(PREV_ENV, None)
    if grow:
        env[GROW_ENV] = "1"
    else:
        env.pop(GROW_ENV, None)
    if max_world:
        env[MAX_WORLD_ENV] = str(max_world)
    else:
        env.pop(MAX_WORLD_ENV, None)

    rendezvous: List[str] = []
    if nranks > 1:
        rendezvous = ["--coordinator", f"127.0.0.1:{free_port()}"]
    procs, logs = [], []
    for rank in range(nranks):
        cmd = [sys.executable, "-m", "pytorch_distributed_mnist_tpu",
               *child_argv]
        if nranks > 1:
            cmd += [*rendezvous, "--num-processes", str(nranks),
                    "--process-id", str(rank)]
        if rank == 0:
            procs.append(subprocess.Popen(cmd, env=env))
            logs.append(None)
        else:
            # Temp files, not pipes: a filled pipe buffer would deadlock
            # a chatty child against a parent that reads at the end.
            log = tempfile.TemporaryFile(mode="w+")
            procs.append(subprocess.Popen(
                cmd, env=env, stdout=log, stderr=subprocess.STDOUT))
            logs.append(log)

    started = time.monotonic()
    first_bad_exit: Optional[float] = None
    stragglers: List[int] = []
    try:
        while True:
            rcs = [p.poll() for p in procs]
            if all(rc is not None for rc in rcs):
                break
            now = time.monotonic()
            if first_bad_exit is None and any(
                    rc is not None and rc != 0 for rc in rcs):
                first_bad_exit = now
            over_settle = (first_bad_exit is not None
                           and now - first_bad_exit > settle_timeout)
            over_total = now - started > generation_timeout
            if over_settle or over_total:
                why = ("settle deadline" if over_settle
                       else "generation timeout")
                for rank, p in enumerate(procs):
                    if p.poll() is None:
                        _say(f"generation {generation}: rank {rank} (host "
                             f"{members[rank]}) still running past the {why} "
                             f"({settle_timeout if over_settle else generation_timeout:g}s); killing it")
                        stragglers.append(rank)
                break
            time.sleep(0.2)
    finally:
        # Every exit path — normal drain, deadline kill, or an exception
        # mid-wait (KeyboardInterrupt included) — reaps every child: an
        # unreaped rank would keep its TPU chips allocated long past the
        # generation (the thread-lifecycle protected-reap rule).
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            p.wait()

    result = GenerationResult(
        generation=generation, members=list(members),
        returncodes=[p.returncode for p in procs], stragglers=stragglers,
    )
    for rank in range(nranks):
        path = record_path(rendezvous_dir, generation, rank)
        if os.path.isfile(path):
            try:
                with open(path) as f:
                    result.records[rank] = json.load(f)
            except (OSError, json.JSONDecodeError) as exc:
                _say(f"generation {generation}: unreadable survivor "
                     f"record for rank {rank} ({exc!r}); counting it dead")
    for rank, log in enumerate(logs):
        if log is None:
            continue
        try:
            log.seek(0)
            result.log_tails[rank] = log.read()[-4000:]
        finally:
            log.close()
    return result


def supervise(
    nprocs: int,
    argv: Sequence[str],
    *,
    min_world: int = 1,
    max_world: int = 0,
    grow: bool = False,
    rejoin: Sequence[Tuple[int, int]] = (),
    settle_timeout: float = 60.0,
    generation_timeout: float = 600.0,
    rendezvous_dir: Optional[str] = None,
) -> int:
    """Run an elastic local world: spawn ``nprocs`` ranks, and on a host
    loss rebuild the survivors into a smaller world resumed from the
    last published checkpoint — and, when join records land in the
    rendezvous dir, rebuild the world LARGER the same way — until the
    job completes or cannot continue. Returns a process exit code (0 =
    the job trained to completion on whatever world remained).

    ``grow`` (``--elastic-grow``) additionally makes every generation
    run the epoch-boundary grow rendezvous, so joiners are admitted
    between epochs instead of only riding failure rebuilds. ``max_world``
    (``--max-world``, 0 = unbounded) caps the grown size. ``rejoin`` is
    the local-simulation hook behind ``tools/chaos.py --rejoin``: for
    each ``(host, generation)`` pair the supervisor writes that host's
    join record just before spawning that generation — deterministic
    stand-in for a replacement host announcing itself while generation
    ``g`` runs.

    The local twin of a cluster manager's restart policy, driven by
    ``tpu-mnist --spawn N --elastic [--min-world M] [--elastic-grow]``
    and ``tools/chaos.py --elastic``. Non-shrink failures propagate: a
    generation that fails with no survivor records and no one killed
    (a symmetric agreed abort, a bad flag) exits with that failure's
    code rather than thrashing through rebuild attempts.
    """
    if nprocs < 2:
        raise ValueError(
            f"elastic supervision needs an initial world of >= 2 "
            f"processes, got {nprocs}")
    if min_world < 1:
        raise ValueError(f"--min-world must be >= 1, got {min_world}")
    if min_world > nprocs:
        raise ValueError(
            f"--min-world {min_world} exceeds the initial world size "
            f"{nprocs}")
    if max_world < 0 or (max_world and max_world < nprocs):
        raise ValueError(
            f"--max-world {max_world} is below the initial world size "
            f"{nprocs} (0 = unbounded)")
    base_argv = strip_spawn_flag(strip_elastic_flags(argv))
    own_dir = rendezvous_dir is None
    if own_dir:
        rendezvous_dir = tempfile.mkdtemp(prefix="tpumnist-elastic-")
    members = list(range(nprocs))
    prev: Optional[List[int]] = None
    generation = 0
    rc: Optional[int] = None

    def _admit_joiners(new_members: List[int]) -> List[int]:
        """Read, plan, and consume pending join records against the
        next world's membership; returns the (possibly grown) member
        list. Stale records (hosts already members) are consumed too —
        a host's pre-loss announcement must never readmit it after a
        later death; deferred-by---max-world records stay pending."""
        pending = pending_joins(rendezvous_dir)
        if not pending:
            return new_members
        paths = dict(pending)
        grown, admitted, stale = plan_grow(
            new_members, [h for h, _ in pending], max_world)
        for host in admitted + stale:
            try:
                os.remove(paths[host])
            except OSError:
                pass  # consumed logically either way
        if stale:
            _say(f"ignoring stale join record(s) for host(s) {stale} "
                 f"(already members)")
        deferred = sorted(set(h for h, _ in pending)
                          - set(admitted) - set(stale))
        if deferred:
            _say(f"join record(s) for host(s) {deferred} deferred: "
                 f"--max-world {max_world} caps the world; they stay "
                 f"pending for a later boundary")
        if admitted:
            _say(f"admitting joiner host(s) {admitted} into the next "
                 f"generation")
        return grown

    def _loop() -> int:
        nonlocal members, prev, generation
        while True:
            child_argv = list(base_argv)
            if generation > 0:
                child_argv = _strip_resume(child_argv) + ["--resume", "auto"]
            for host, at_generation in rejoin:
                # The chaos/test hook: this host's join record lands
                # while generation `at_generation` runs (written just
                # before the spawn — deterministic, and exactly what a
                # real replacement host would do via announce_join).
                if at_generation == generation:
                    announce_join(rendezvous_dir, host)
                    _say(f"host {host} announced a join (rejoin hook); "
                         f"admitted at the next generation boundary")
            _say(f"generation {generation}: world size {len(members)} "
                 f"(hosts {members})"
                 + (", resuming from the last published checkpoint"
                    if generation else ""))
            result = _run_generation(
                generation, members, child_argv, rendezvous_dir, prev,
                settle_timeout, generation_timeout, grow=grow,
                max_world=max_world)
            if result.clean:
                _say(f"generation {generation}: trained to completion "
                     f"on world size {len(members)}")
                return 0
            # EXIT_GROW is a healthy planned yield, not a failure: map
            # it to a clean exit for the membership plan (a yield record
            # normally proves it too, but the exit code alone suffices
            # when the record write failed).
            yielded = (
                any(rc == EXIT_GROW for rc in result.returncodes)
                or any(rec.get("yield") for rec in result.records.values())
            )
            survivors, dead = plan_next_world(
                len(members),
                [0 if rc == EXIT_GROW else rc
                 for rc in result.returncodes],
                list(result.records))
            dead_hosts = [members[r] for r in dead]
            for rank in dead:
                tail = result.log_tails.get(rank)
                if tail:
                    print(f"--- generation {generation} rank {rank} "
                          f"(host {members[rank]}) died "
                          f"(rc={result.returncodes[rank]}) ---\n{tail}",
                          file=sys.stderr, flush=True)
            if not dead and not yielded:
                # Everyone claims survival yet the generation failed:
                # a symmetric abort (divergence SystemExit, vote
                # rejection). There is nothing to shrink around.
                _say(f"generation {generation}: failed with no dead "
                     f"host (symmetric abort); not a shrink event")
                return result.first_bad_rc()
            if not survivors:
                _say(f"generation {generation}: no survivors (every "
                     f"rank died or left no record); the world is gone")
                return result.first_bad_rc()
            new_members = [members[r] for r in survivors]
            disagreements = {
                rank: rec["dead_hosts"] for rank, rec in
                sorted(result.records.items())
                if rec.get("dead_hosts") and
                set(rec["dead_hosts"]) - set(dead_hosts)
            }
            if disagreements:
                # Expected for watchdog/timeout attributions (a host
                # blocked in an agreement cannot see WHICH peer is
                # missing, so it implicates every other host); a pill
                # names the one true failer. Either way a record is
                # proof of a live unwind, so an implicated host that
                # demonstrably voted survives — surfaced, not obeyed.
                _say(f"generation {generation}: record dead-sets "
                     f"disagree with observed exits ({disagreements} vs "
                     f"{dead_hosts}); trusting observed exits")
            # Joiners ride EVERY generation boundary: the planned grow
            # rendezvous, and any failure rebuild a replacement arrived
            # during (admitted before the floor check on purpose — a
            # loss whose replacement already announced keeps the world
            # at or above the floor).
            new_members = _admit_joiners(new_members)
            if len(new_members) < min_world:
                _say(f"generation {generation}: host(s) {dead_hosts} "
                     f"lost; {len(new_members)} survivor(s) "
                     f"{new_members} is below --min-world {min_world} "
                     f"— exiting ({EXIT_FLOOR}) instead of training on "
                     f"a world the operator ruled out")
                return EXIT_FLOOR
            if yielded and not dead and new_members == members:
                # A yield with nothing to admit (the joiner's record
                # vanished between the workers' check and this plan):
                # relaunch the same world — never an error, never a
                # tight loop (the next yield needs a fresh join record;
                # the --max-world-deferred case cannot reach here, the
                # workers skip the rendezvous at the cap).
                _say(f"generation {generation}: grow rendezvous found "
                     f"nothing to admit; relaunching the same world")
            elif dead:
                _say(f"generation {generation}: host(s) {dead_hosts} "
                     f"lost in phase(s) "
                     f"{sorted({rec.get('phase', '?') for rec in result.records.values()}) or '?'}"
                     f"; survivors {[members[r] for r in survivors]} "
                     f"agree — rebuilding at world size "
                     f"{len(new_members)} (members {new_members})")
            else:
                _say(f"generation {generation}: grow rendezvous — "
                     f"rebuilding at world size {len(new_members)} "
                     f"(members {new_members}), resumed from the last "
                     f"published checkpoint")
            prev, members = members, new_members
            generation += 1

    try:
        rc = _loop()
        return rc
    finally:
        if own_dir:
            if rc == 0:
                import shutil

                shutil.rmtree(rendezvous_dir, ignore_errors=True)
            else:
                # The records ARE the membership evidence: keep them
                # for the postmortem of a run that could not continue.
                _say(f"survivor records kept for postmortem: "
                     f"{rendezvous_dir}")
