"""Run supervision: the agreed-exit protocol, collective watchdogs, and
fault-injection hooks.

The framework's failure model (docs/DESIGN.md "Failure model") rests on
one invariant: **no host may fail alone on a path its peers continue past
into a collective** — multi-host collectives have no timeout, so a lone
local error strands every peer forever. Before this module, the invariant
was enforced piecewise: ``_agree_phase_ok`` in train/checkpoint.py, plus
the same shape inlined twice in cli.py. This module is the one wiring all
three pieces now share:

- **Agreement records** (``allgather_records`` / ``agree``): every
  host-side exchange — checkpoint phase agreements, the dataset vote,
  resume resolution (the old one-to-all broadcast retired into this
  channel) and the resume-load agreement — is one fixed-width record per
  host: a ``K``/``E``/``P`` status byte + the host's current phase + a
  detail string, instead of a bare ok bool. Because every exchange is
  the SAME program shape, a failing host's poison-pill record meets
  whatever agreement its peers reach next and still parses: peers learn
  *who* failed, *where*, and *why*, and raise ``PeerFailure`` naming all
  three.
- **Agreed exit** (``deliver_poison``): ``cli.run`` routes every
  host-local failure (data staging, step execution, checkpoint phases,
  eval) through one except-path that participates in the next agreement
  collective with a ``P`` record before unwinding — converting "peers
  hang at the next drain" (the ADVICE.md residual hazard) into "peers
  exit with ``PeerFailure(host, phase, reason)``".
- **Watchdogs** (``utils/watchdog.py``): every agreement collective
  gets a configurable deadline
  (``--agreement-timeout`` / ``TPUMNIST_AGREEMENT_TIMEOUT``; 0 = off,
  the default on real multi-host TPU where a slow-but-healthy job must
  not be shot). On expiry the supervisor dumps a per-host phase report —
  which phase this host is blocked in, for how long, and each peer's
  last-heartbeat (the phase it reported at the last completed agreement)
  — then aborts with ``PeerFailure`` attributing the silent peers.
- **Fault injection** (``FaultPlan`` / ``maybe_fault``): named fault
  points throughout the stack honor ``TPUMNIST_FAULT=point:host:kind``
  so the chaos harness (tools/chaos.py, tests/test_chaos.py) can kill,
  raise in, or stall a chosen process at a chosen point and prove the
  protocol end to end with real subprocess twins.

What the protocol can and cannot promise: a poison pill unwinds peers
cleanly when their next *cross-host host-side operation* is an agreement
collective (every checkpoint phase, the resume agreements, the dataset
agreement). A peer blocked inside a *device* program (a train step's
psum) cannot be reached by any host-side protocol — that case stays with
the watchdog/coordination-service layer and the restart-from-checkpoint
recovery model. The residual-hazards table in docs/DESIGN.md is the
authoritative list.
"""

from __future__ import annotations

import os
import signal
import sys
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from pytorch_distributed_mnist_tpu.parallel.distributed import (
    process_count,
    process_index,
)
from pytorch_distributed_mnist_tpu.utils.profiling import failure_events
from pytorch_distributed_mnist_tpu.utils.watchdog import (
    WatchdogTimeout,
    run_with_deadline,
)

# Fixed per-host agreement record: 1 status byte + "phase\x1fdetail",
# NUL-padded. EVERY host-side supervision collective — checkpoint phase
# agreements, the dataset vote, resume resolution AND load agreement, and
# the poison pill — exchanges exactly this shape, so order-mismatched
# collectives (a poison pill meeting whatever agreement the peers reach
# next) still execute the same program and parse cleanly.
#
# Status bytes (non-NUL on purpose — rstrip-safe): ``K`` ok, ``E`` this
# host's local outcome for THIS agreement was a failure (a vote), ``P``
# this host is dying on a host-local error and this record is its poison
# pill (fatal regardless of which agreement it lands in).
RECORD_BYTES = 4352
# Payload capacity of one record's detail field (status byte + phase cap
# + separator reserve the rest). Derived, not a second literal: callers
# that budget-check what they stuff into a detail (the resume-resolution
# path) must track a record resize automatically. Sized so the old
# resume broadcast's 4095-byte path budget still fits.
DETAIL_BYTES = RECORD_BYTES - 160
_SEP = b"\x1f"
_OK, _ERR, _POISON = b"K", b"E", b"P"

# Environment knobs (documented in README "what happens when a host dies").
TIMEOUT_ENV = "TPUMNIST_AGREEMENT_TIMEOUT"
FAULT_ENV = "TPUMNIST_FAULT"
# A failing host's poison-pill allgather must itself be bounded even when
# agreement watchdogs are off — if its peers are stuck in a device
# collective they will never meet it, and the failing host must not trade
# its clean exit for a new hang.
POISON_TIMEOUT_DEFAULT = 60.0


class PeerFailure(RuntimeError):
    """Another host failed (or went silent) and this host must unwind.

    ``hosts`` is the list of implicated process indices, ``phase`` the
    failure phase being attributed (the peer's own reported phase when it
    delivered a record; the local agreement's phase on a watchdog
    timeout), ``reason`` a short human string. ``already_agreed`` tells
    the agreed-exit path not to send a poison pill for this exception:
    the peers either already know (they sent the record) or are beyond
    reach (they timed out).
    """

    already_agreed = True

    def __init__(self, message: str, *, hosts: List[int], phase: str,
                 reason: str = "") -> None:
        super().__init__(message)
        self.hosts = list(hosts)
        self.phase = phase
        self.reason = reason


class InjectedFault(RuntimeError):
    """Raised by a ``kind=raise`` fault point (chaos harness)."""


# ---------------------------------------------------------------------------
# Fault injection
# ---------------------------------------------------------------------------

# Every injectable fault point in the framework, name -> where it fires.
# tools/chaos.py --list renders this table, and tests/test_supervision.py
# pins that every maybe_fault() call site in the source appears here (and
# vice versa), so hooks and docs cannot drift.
FAULT_POINTS: Dict[str, str] = {
    "data_stage": "cli._build_loaders entry: dataset load/staging on this "
                  "host, before the cross-host dataset agreement",
    "train_epoch": "Trainer.train entry: host-side work of one training "
                   "epoch (staging, dispatch)",
    "train_step": "Trainer.train per-batch loop (stepwise/explicit "
                  "modes): before each step's dispatch, so kills land "
                  "BETWEEN device programs mid-epoch (scan mode runs "
                  "the epoch as one program — no per-step host "
                  "boundary to hook)",
    "eval": "Trainer.evaluate entry: host-side work of one eval pass",
    "ckpt_prepare": "checkpoint._sharded_prepare entry: tmp-dir cleanup "
                    "before the prepare agreement",
    "ckpt_collect": "checkpoint sharded-save collect phase: owned-shard "
                    "D2H snapshot, before the write agreement",
    "ckpt_write": "checkpoint._sharded_write_files entry: shard/index/"
                  "meta file I/O (the async writer thread's phase)",
    "ckpt_publish": "checkpoint._sharded_publish entry: immediately "
                    "before the publish agreement collective",
    "resume": "cli resume section entry: before checkpoint resolution "
              "and the resume broadcast/agreement",
    "download_fetch": "data.download._fetch entry: one mirror fetch "
                      "attempt",
    "elastic_rebuild": "runtime/elastic.py survivor-record write: a "
                       "surviving worker between its PeerFailure and "
                       "its shrink exit — kill/stall here is a SECOND "
                       "failure during the world rebuild (the "
                       "supervisor must shrink further, never hang)",
}

_FAULT_KINDS = ("kill", "raise", "stall")


@dataclass
class FaultPlan:
    """One injected fault: ``point:host:kind[:arg]``.

    ``host`` is a process index or ``*`` (every host). ``kind``:
    ``kill`` (SIGKILL this process — the preemption case), ``raise``
    (raise ``InjectedFault`` — the host-local error case), ``stall``
    (sleep ``arg`` seconds, default 3600 — the silent-peer case). For
    ``kill``/``raise``, ``arg`` is instead the number of matching hits to
    SKIP before firing (so "the second epoch's train staging" is
    ``train_epoch:*:kill:1``).
    """

    point: str
    host: str
    kind: str
    arg: float = 0.0

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        if "," in spec:
            raise ValueError(
                f"bad {FAULT_ENV} spec {spec!r}: one fault per spec "
                f"(comma-join multiple specs and parse with "
                f"parse_fault_specs)"
            )
        parts = spec.split(":")
        if len(parts) not in (3, 4):
            raise ValueError(
                f"bad {FAULT_ENV} spec {spec!r}: expected "
                f"point:host:kind[:arg]"
            )
        point, host, kind = parts[:3]
        if point not in FAULT_POINTS:
            raise ValueError(
                f"bad {FAULT_ENV} spec {spec!r}: unknown fault point "
                f"{point!r} (tools/chaos.py --list enumerates them)"
            )
        if kind not in _FAULT_KINDS:
            raise ValueError(
                f"bad {FAULT_ENV} spec {spec!r}: unknown kind {kind!r} "
                f"(one of {', '.join(_FAULT_KINDS)})"
            )
        if host != "*":
            try:
                int(host)
            except ValueError:
                raise ValueError(
                    f"bad {FAULT_ENV} spec {spec!r}: host must be a "
                    f"process index or '*'"
                ) from None
        arg = float(parts[3]) if len(parts) == 4 else (
            3600.0 if kind == "stall" else 0.0)
        return cls(point=point, host=host, kind=kind, arg=arg)

    def matches(self, point: str) -> bool:
        if point != self.point:
            return False
        return self.host == "*" or int(self.host) == process_index()

    def fire(self) -> None:
        detail = f"{self.point}:{self.host}:{self.kind}"
        print(f"chaos: process {process_index()} firing injected fault "
              f"{detail}", file=sys.stderr, flush=True)
        if self.kind == "kill":
            sys.stderr.flush()
            sys.stdout.flush()
            os.kill(os.getpid(), signal.SIGKILL)
            return  # unreachable
        if self.kind == "stall":
            time.sleep(self.arg)
            return
        raise InjectedFault(f"injected fault at {detail} (chaos harness)")


def parse_fault_specs(spec: str) -> List[FaultPlan]:
    """Parse a comma-joined list of fault specs (the ``TPUMNIST_FAULT``
    surface): ``point:host:kind[:arg][,point:host:kind[:arg]...]``.

    Multiple plans exist for the mid-rebuild chaos scenarios: the first
    spec injects the host loss, the second sabotages a SURVIVOR during
    the shrink (``elastic_rebuild``). Host indices are process ranks
    within the world that reads the plan — in an elastic run, each
    generation's ranks, not stable host ids (tools/chaos.py documents
    the caveat)."""
    plans = [FaultPlan.parse(part) for part in spec.split(",")
             if part.strip()]
    if not plans and spec.strip():
        raise ValueError(f"bad {FAULT_ENV} spec {spec!r}: no fault specs")
    return plans


_fault_plans: List[FaultPlan] = []
_fault_parsed = False
_fault_hits: Dict[str, int] = {}


def _load_fault_plans() -> List[FaultPlan]:
    global _fault_plans, _fault_parsed
    if not _fault_parsed:
        spec = os.environ.get(FAULT_ENV, "").strip()
        _fault_plans = parse_fault_specs(spec) if spec else []
        _fault_parsed = True
    return _fault_plans


def maybe_fault(point: str) -> None:
    """Fire the first configured fault whose point/host matches.

    Call sites must use a string literal from ``FAULT_POINTS`` (pinned by
    test); the hook is a no-op (one dict probe) when no plan is set.
    Matching hits are counted PER POINT (shared across plans targeting
    the same point — one plan per point is the supported shape).
    """
    assert point in FAULT_POINTS, f"unregistered fault point {point!r}"
    plans = [p for p in _load_fault_plans() if p.matches(point)]
    if not plans:
        return
    hits = _fault_hits.get(point, 0)
    _fault_hits[point] = hits + 1
    for plan in plans:
        if plan.kind in ("kill", "raise") and hits < int(plan.arg):
            continue  # arg = number of matching hits to skip first
        plan.fire()
        return


# ---------------------------------------------------------------------------
# Supervisor state
# ---------------------------------------------------------------------------

_timeout: float = 0.0
_hard_exit_after: Optional[float] = None
_phase: str = "startup"
_agreements: int = 0
# host -> {"phase": str, "agreement": int, "wall": float} from the last
# completed agreement: the per-host heartbeat the watchdog dump renders.
_last_seen: Dict[int, Dict] = {}


def configure(timeout: Optional[float] = None,
              hard_exit_after: Optional[float] = 30.0) -> float:
    """(Re)arm the supervisor for one run; returns the effective timeout.

    Resolution: explicit ``timeout`` (the ``--agreement-timeout`` flag) >
    ``TPUMNIST_AGREEMENT_TIMEOUT`` env > 0 (off). 0/negative disables the
    watchdogs; the agreement protocol itself (records, poison pills) is
    always on. Also resets per-run state (phase, heartbeats, fault-plan
    cache) so re-entrant ``cli.run`` calls supervise their own run only.
    """
    global _timeout, _hard_exit_after, _phase, _agreements
    global _fault_parsed, _fault_plans
    if timeout is None:
        env = os.environ.get(TIMEOUT_ENV, "").strip()
        try:
            timeout = float(env) if env else 0.0
        except ValueError:
            raise SystemExit(
                f"{TIMEOUT_ENV}={env!r} is not a number of seconds"
            )
    _timeout = max(0.0, float(timeout))
    _hard_exit_after = hard_exit_after
    _phase = "startup"
    _agreements = 0
    _last_seen.clear()
    _fault_parsed = False
    _fault_plans = []
    _fault_hits.clear()
    return _timeout


def agreement_timeout() -> float:
    return _timeout


def set_phase(phase: str) -> str:
    """Mark the lifecycle phase this host is entering (diagnostics +
    poison-pill attribution); returns the previous phase."""
    global _phase
    prev, _phase = _phase, phase
    return prev


def current_phase() -> str:
    return _phase


def _dump_phase_report(label: str, started: float) -> None:
    """The watchdog diagnostic: who we are, where we're stuck, and every
    peer's last heartbeat. stderr, one block, machine-greppable header."""
    from pytorch_distributed_mnist_tpu.parallel.distributed import (
        runtime_info,
    )

    info = runtime_info()
    topo = ", ".join(f"{k}={info[k]}" for k in sorted(info)
                     if k != "initialized_at")
    lines = [
        f"=== supervision watchdog report (process {process_index()}) ===",
        f"world: {topo}",
        f"blocked in: {label}",
        f"lifecycle phase: {_phase}",
        f"waited: {time.time() - started:.1f}s "
        f"(deadline {_timeout:g}s)",
        f"completed agreements this run: {_agreements}",
    ]
    if _last_seen:
        lines.append("per-host last heartbeat (phase reported at the "
                     "last completed agreement):")
        for host in sorted(_last_seen):
            rec = _last_seen[host]
            age = time.time() - rec["wall"]
            lines.append(
                f"  host {host}: phase {rec['phase']!r} at agreement "
                f"#{rec['agreement']}, {age:.1f}s ago"
            )
    else:
        lines.append("no completed agreements yet: peers' phases unknown "
                     "(a host may have died before the first agreement)")
    lines.append("which hosts reached this collective cannot be observed "
                 "from inside it; suspects = every host but this one")
    print("\n".join(lines), file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# Agreement collectives
# ---------------------------------------------------------------------------


@dataclass
class Record:
    """One host's decoded agreement record."""

    status: str  # "K" | "E" | "P"
    phase: str   # the sender's lifecycle phase when it sent the record
    detail: str

    @property
    def ok(self) -> bool:
        return self.status == "K"

    @property
    def poisoned(self) -> bool:
        return self.status == "P"


def _encode_record(status: bytes, detail: str) -> bytes:
    body = status + _phase.encode()[:128] + _SEP \
        + detail.encode()[:DETAIL_BYTES]
    return body.ljust(RECORD_BYTES, b"\0")


def _decode_record(raw: bytes) -> Record:
    raw = raw.rstrip(b"\0")
    status = raw[:1].decode(errors="replace") or "?"
    phase, _, detail = raw[1:].partition(_SEP)
    return Record(status, phase.decode(errors="replace"),
                  detail.decode(errors="replace"))


def _raw_allgather(payload: np.ndarray) -> np.ndarray:
    """One process_allgather; split out so tests can stall/patch it."""
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(payload)


def _collective_with_deadline(fn: Callable, label: str):
    """Run a host collective under the configured watchdog deadline."""
    started = time.time()
    return run_with_deadline(
        fn, timeout=_timeout, label=label,
        on_timeout=lambda: _dump_phase_report(label, started),
        hard_exit_after=_hard_exit_after,
    )


def allgather_records(phase: str, ok: bool, detail: str = "",
                      fatal: bool = False) -> List[Record]:
    """Exchange one supervision record per host; returns decoded records
    indexed by process. Single-process: returns this host's record alone
    (no collective). On watchdog expiry: dumps the phase report and
    raises ``PeerFailure`` implicating every other host.
    """
    global _agreements
    status = _POISON if fatal else (_OK if ok else _ERR)
    record = _encode_record(status, detail)
    if process_count() <= 1:
        return [_decode_record(record)]
    payload = np.frombuffer(record, dtype=np.uint8)
    label = f"agreement '{phase}'"
    try:
        gathered = _collective_with_deadline(
            lambda: _raw_allgather(payload), label)
    except WatchdogTimeout as exc:
        suspects = [h for h in range(process_count())
                    if h != process_index()]
        failure_events.record(
            "agreement_timeout", f"{label}: peers silent past "
            f"{_timeout:g}s deadline", phase=phase, hosts=suspects)
        raise PeerFailure(
            f"PeerFailure: agreement {phase!r} timed out after "
            f"{_timeout:g}s — host(s) {suspects} never arrived (died or "
            f"stuck outside an agreed phase); see the watchdog report "
            f"above for per-host last heartbeats",
            hosts=suspects, phase=phase,
            reason="agreement deadline exceeded",
        ) from exc
    except Exception as exc:
        # The collective itself failed in TRANSPORT (gloo "connection
        # reset by peer", a dead coordinator's grpc socket): a peer died
        # mid-collective. That is a peer failure, not a host-local error
        # — attributing it (and marking it already-agreed) matters
        # doubly, because a poison pill sent for it would block in the
        # same dead transport while jax's coordination service races to
        # hard-kill this process.
        suspects = [h for h in range(process_count())
                    if h != process_index()]
        failure_events.record(
            "agreement_transport_error", f"{label}: {exc!r}",
            phase=phase, hosts=suspects)
        raise PeerFailure(
            f"PeerFailure: agreement {phase!r} failed in transport — "
            f"host(s) {suspects} likely died mid-collective: {exc!r}",
            hosts=suspects, phase=phase,
            reason=f"collective transport failure: {exc!r}"[:300],
        ) from exc
    gathered = np.asarray(gathered).reshape(process_count(), RECORD_BYTES)
    records = [_decode_record(gathered[h].tobytes())
               for h in range(process_count())]
    _agreements += 1
    now = time.time()
    for host, rec in enumerate(records):
        _last_seen[host] = {"phase": rec.phase, "agreement": _agreements,
                            "wall": now}
    return records


def agree(phase: str, error: Optional[BaseException] = None,
          detail: str = "") -> List[Tuple[int, str, str]]:
    """Agree a per-host phase outcome; returns failed peers' records.

    Every host calls this at the same logical step with its local outcome
    (``error`` / ``detail``). Returns ``[(host, peer_phase, reason), ...]``
    for every FAILED host (``E`` votes and ``P`` poison pills alike) so
    callers can raise their own domain-specific message
    (train/checkpoint.py keeps its pinned wording); callers must re-raise
    ``error`` afterwards when it is set. The allgather itself
    synchronizes, so callers may rely on this as a barrier.
    """
    detail = detail or (repr(error) if error is not None else "")
    records = allgather_records(phase, error is None, detail)
    if error is not None:
        # The E record above WAS this error's delivery to the peers: the
        # agreed-exit path must not send a second pill for it on unwind
        # (a pill no peer would pair a collective with).
        mark_agreed(error)
    return [(host, rec.phase, rec.detail)
            for host, rec in enumerate(records) if not rec.ok]


def mark_agreed(error: BaseException) -> None:
    """Mark ``error`` as already communicated to the peers, so
    ``deliver_poison`` will not send a (count-misaligning) second pill
    for it. Callers that raise AFTER an agreement that every host
    reached — divergence SystemExits, vote rejections — must mark what
    they raise: every host leaves that agreement raising something, so
    nobody is left to pair a collective with a pill."""
    try:
        error._poison_delivered = True
    except AttributeError:
        pass  # exceptions with __slots__: worst case a duplicate pill


def raise_if_poisoned(records: List[Record], context: str) -> None:
    """Raise ``PeerFailure`` when any record is a peer's poison pill.

    Vote-type agreements (dataset load, resume resolution/outcome)
    interpret a same-phase ``E`` record as a legitimate local vote;
    without this check a dying peer's pill would be misread as that vote
    ("dataset not present on host 2") instead of the truth ("host 2 died
    in checkpoint write"). The ``P`` status makes the distinction
    explicit whatever phase the pill was sent from.
    """
    poisoned = [(host, rec.phase, rec.detail)
                for host, rec in enumerate(records)
                if rec.poisoned and host != process_index()]
    if poisoned:
        raise PeerFailure(
            peer_failure_message(
                poisoned,
                f"PeerFailure: host(s) {[h for h, _, _ in poisoned]} "
                f"died on a host-local error while this host was in "
                f"{context};",
            ),
            hosts=[h for h, _, _ in poisoned],
            phase=poisoned[0][1],
            reason=poisoned[0][2],
        )


def peer_failure_message(failed: List[Tuple[int, str, str]],
                         context: str) -> str:
    """Uniform rendering of failed-peer records for error messages."""
    per_host = "; ".join(
        f"host {h} in phase {p!r}: {r or 'no detail'}"
        for h, p, r in failed
    )
    return f"{context} [{per_host}]"


def escalate_exit(error: BaseException, grace: float = 10.0) -> None:
    """Arm a hard exit for a host dying on a PEER failure.

    When this host unwinds because its peers are dead (``PeerFailure`` /
    watchdog abort — the ``already_agreed`` class), interpreter teardown
    is itself a hang risk: jax's atexit distributed shutdown runs a
    coordination-service *barrier* that the dead peers will never join,
    parking the process ~90s until the heartbeat timeout hard-kills it
    (observed in the chaos twins) — which both delays the exit far past
    the watchdog deadline and replaces the informative exit with a
    SIGABRT. A daemon timer gives normal teardown ``grace`` seconds,
    then ``os._exit``s with the watchdog's distinct code. Symmetric
    failure exits (every host raising the same agreed error) are NOT
    escalated: all hosts reach the shutdown barrier together and a
    normal exit preserves the real return code.
    """
    if process_count() <= 1 or not getattr(error, "already_agreed", False):
        return
    from pytorch_distributed_mnist_tpu.utils.watchdog import arm_hard_exit

    failure_events.record("exit_escalated",
                          f"hard exit in {grace:g}s (peers unreachable)")
    arm_hard_exit(grace, "peers unreachable; the distributed shutdown "
                         "barrier may block interpreter teardown")


def deliver_poison(error: BaseException) -> None:
    """The agreed exit: participate in the next agreement collective with
    a failure record, so peers unwind with ``PeerFailure`` instead of
    hanging at their next agreement.

    No-op when: single-process (nobody to poison); the error is itself
    the product of an agreement (``already_agreed`` — peers already know,
    or timed out and are beyond reach); or ``KeyboardInterrupt`` (the
    operator is killing every host themselves). The poison allgather is
    always deadline-bounded (the configured timeout, else
    ``POISON_TIMEOUT_DEFAULT``): if peers are stuck in a device
    collective they will never meet it, and this host's clean exit must
    not become a second hang. Best-effort by design — the original
    ``error`` is never masked.
    """
    if process_count() <= 1:
        return
    if getattr(error, "already_agreed", False):
        return
    if isinstance(error, KeyboardInterrupt):
        return
    if getattr(error, "_poison_delivered", False):
        # Idempotent per exception: both AsyncCheckpointer.__exit__ and
        # cli.run's supervised scope call this on the same unwind, but
        # the pill must go out exactly once — peers pair ONE extra
        # collective with it, a second would misalign every host's
        # collective count.
        return
    try:
        error._poison_delivered = True
    except AttributeError:
        pass  # exceptions with __slots__: worst case a duplicate pill
    global _timeout
    reason = repr(error)[:300]
    failure_events.record("poison_sent", reason, phase=_phase)
    print(
        f"process {process_index()}: host-local failure in phase "
        f"{_phase!r}; delivering poison pill to peers before exit: "
        f"{reason}", file=sys.stderr, flush=True,
    )
    bounded = _timeout if _timeout > 0 else POISON_TIMEOUT_DEFAULT
    saved, _timeout = _timeout, bounded
    try:
        allgather_records("poison_exit", ok=False, detail=reason,
                          fatal=True)
    except Exception as exc:
        # Peers never met the poison (dead, stuck in a device program,
        # or the transport is already gone). The coordination service /
        # operator restart layer owns them now; this host exits on its
        # original error — delivery is best-effort by contract.
        failure_events.record(
            "poison_undelivered",
            f"no agreement within {bounded:g}s: {exc!r}", phase=_phase)
    finally:
        _timeout = saved
