"""Run-time supervision layer: agreed exits, watchdogs, fault injection."""
