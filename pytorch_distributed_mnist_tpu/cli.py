"""CLI + per-process job driver.

Flag and lifecycle parity with the reference's ``__main__`` + ``run``
(``/root/reference/multi_proc_single_gpu.py:163-255, 288-359``), redesigned
for the TPU runtime:

- kept flags (same names/defaults): ``--root data``, ``-j/--workers 4``,
  ``--epochs 20``, ``--start-epoch 0``, ``--batch-size 256``, ``--lr 1e-3``,
  ``--momentum 0.9``, ``--wd 1e-4``, ``--resume ''``, ``-e/--evaluate``,
  ``--seed`` (``:289-336``);
- replaced flags: ``--backend/--init-method/--local_rank/--rank/
  --world-size`` (torch rendezvous, ``:316-331``) become
  ``--coordinator/--num-processes/--process-id`` feeding
  ``jax.distributed.initialize`` — auto-detected on TPU pods, so none are
  needed in the common case. There is no mode selection by editing source
  (the reference's spawn-vs-launch comment dance, ``:353-359``);
- new flags beyond the reference's surface: ``--model`` (hard-coded at
  ``:185``) / ``--dataset`` (hard-coded MNIST at ``:137``) / ``--dtype`` /
  ``--trainer-mode`` / ``--profile-dir`` / ``--checkpoint-dir``;
  launch: ``--spawn N`` (the ``mp.spawn`` mode as a flag, ``:284-285``);
  kernels: ``--optimizer adam_pallas``, ``--loss fused``,
  ``--attention flash``; parallelism: ``--tensor-parallel``,
  ``--sequence-parallel[-impl]``, ``--pipeline-stages``,
  ``--expert-parallel`` (+ ``--moe-dispatch dense|capacity``,
  ``--moe-aux-weight``),
  ``--optimizer-sharding zero1|zero3``, ``--grad-accum``, ``--remat``;
  checkpoint lifecycle: ``--resume auto``, ``--keep-last``,
  ``--async-checkpoint``; input path: ``--epoch-gather host|device``
  (device-resident dataset + in-program ``jnp.take``);
  observability: ``--metrics-file``, ``--debug-nans``.

Batch-size semantics: the reference's ``--batch-size`` is the per-node total
divided among that node's GPUs (``:174``, ``:297-300``). Here it is the
**global** batch divided among all chips by the mesh — the multi-host
generalization of the same rule, documented instead of implicit.

Lifecycle parity (``run``): distributed init (``:167``), model+optimizer
(``:185-191``), resume (``:197-214``), loaders (``:218-221``),
``--evaluate`` short-circuit (``:225-228``), epoch loop with sampler
reseed + LR step decay + train + eval + best tracking + process-0
checkpoint (``:230-255``).
"""

from __future__ import annotations

import argparse
import os
import random
import sys
from typing import Optional

import jax
import numpy as np

from pytorch_distributed_mnist_tpu.data.loader import MNISTDataLoader
from pytorch_distributed_mnist_tpu.data.mnist import load_dataset, normalize_images
from pytorch_distributed_mnist_tpu.models import get_model, list_models, model_accepts
from pytorch_distributed_mnist_tpu.parallel.distributed import (
    initialize_distributed,
    process_count,
    process_index,
)
from pytorch_distributed_mnist_tpu.parallel.mesh import (
    data_replica_coords,
    make_mesh,
)
from pytorch_distributed_mnist_tpu.runtime import elastic, supervision
from pytorch_distributed_mnist_tpu.train.checkpoint import (
    is_corrupt_checkpoint_error,
    quarantine_checkpoint,
    save_checkpoint,
    try_resume,
)
from pytorch_distributed_mnist_tpu.train.lr_schedule import step_decay_schedule
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from pytorch_distributed_mnist_tpu.train.trainer import Trainer
from pytorch_distributed_mnist_tpu.utils import compile_cache
from pytorch_distributed_mnist_tpu.utils.logging import log0
from pytorch_distributed_mnist_tpu.utils.profiling import (
    StepTimer,
    compile_log,
    failure_events,
    phase,
    profile_trace,
    staging_log,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu-mnist",
        description="TPU-native distributed MNIST training (JAX/XLA/pjit)",
        # No prefix abbreviation: an abbreviated '--spaw 2' would set
        # args.spawn here yet survive launcher.strip_spawn_flag's literal
        # match, so children would re-parse it next to the injected
        # --coordinator and die with a confusing combination error.
        allow_abbrev=False,
    )
    # Reference-parity flags (defaults match :289-336).
    p.add_argument("--root", type=str, default="data", help="dataset root dir")
    p.add_argument("-j", "--workers", type=int, default=4,
                   help="data-loader worker threads (used by the native "
                        "loader backend when built; no-op otherwise)")
    p.add_argument("--epochs", type=int, default=20)
    p.add_argument("--start-epoch", type=int, default=0)
    p.add_argument("--batch-size", type=int, default=256,
                   help="GLOBAL batch size, split across all chips")
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--momentum", type=float, default=0.9, help="for --optimizer sgd")
    p.add_argument("--wd", "--weight-decay", type=float, default=1e-4,
                   dest="weight_decay", help="for --optimizer sgd")
    p.add_argument("--resume", type=str, default="",
                   help="checkpoint path to resume from, or 'auto' to pick "
                        "the newest checkpoint in --checkpoint-dir (trains "
                        "fresh when none exists yet — the same command line "
                        "works for first launch and every restart)")
    p.add_argument("-e", "--evaluate", action="store_true",
                   help="evaluate on the test set and exit")
    p.add_argument("--seed", type=int, default=None)
    # Distributed bootstrap (replaces --backend/--init-method/--rank/--world-size).
    p.add_argument("--coordinator", type=str, default=None,
                   help="coordinator address host:port for multi-host runs")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("--spawn", type=int, default=0, metavar="N",
                   help="fork N local host processes (one CPU device each) "
                        "that rendezvous on a free loopback port — the "
                        "reference's mp.spawn launch mode (:284-285), here "
                        "as a flag instead of a source edit. Local "
                        "simulation of an N-host pod; real pods need no "
                        "spawner (one process per host already)")
    # TPU-framework extensions.
    p.add_argument("--model", type=str, default="cnn", choices=list_models())
    p.add_argument("--attention", type=str, default="dense",
                   choices=["dense", "flash"],
                   help="core attention impl for --model vit: dense XLA "
                        "softmax or the Pallas flash kernel (ring/ulysses "
                        "sequence parallelism are library APIs, see "
                        "parallel/ring.py)")
    p.add_argument("--dataset", type=str, default="mnist",
                   choices=["mnist", "fashion_mnist", "synthetic"])
    p.add_argument("--download", action="store_true",
                   help="fetch + verify the dataset's IDX files into --root "
                        "when absent (reference :137-138 download=True; for "
                        "multi-host runs, pre-download with a single-process "
                        "run first, as the reference README does)")
    p.add_argument("--allow-synthetic", action="store_true",
                   help="if the real dataset is missing (and --download "
                        "absent or failed), fall back to the labelled "
                        "synthetic dataset instead of exiting. Without "
                        "this flag a missing dataset is a hard error — "
                        "the reference always downloads (:137-138), so "
                        "silently training on fake data would invert its "
                        "contract and produce fake accuracy numbers")
    p.add_argument("--dtype", type=str, default=None,
                   choices=["bf16", "f32"],
                   help="compute dtype override. linear/cnn/vit default to "
                        "bfloat16 activations with float32 params/logits "
                        "(the MXU-native policy); the MoE models default "
                        "to f32 (router numerics). f32 forces "
                        "full-precision compute everywhere for numerics "
                        "debugging or CPU parity runs")
    p.add_argument("--optimizer", type=str, default="adam",
                   choices=["adam", "adam_pallas", "sgd"],
                   help="adam_pallas = fused Pallas update kernel")
    p.add_argument("--loss", type=str, default="xla",
                   choices=["xla", "fused"],
                   help="cross-entropy impl: xla (compiler-fused, "
                        "GSPMD-partitionable, default) or fused (the "
                        "Pallas single-pass kernel, ops/pallas/xent.py, "
                        "embedded in GSPMD programs via a nested "
                        "shard_map over the data axis; composes with "
                        "DP/TP/SP but not --pipeline-stages)")
    p.add_argument("--pipeline-stages", type=int, default=1,
                   help="pipeline-parallel stages for --model vit (GPipe "
                        "over a 'stage' mesh axis; devices are split "
                        "data x stage, vit depth must divide evenly)")
    p.add_argument("--tensor-parallel", type=int, default=1,
                   help="tensor-parallel width for --model vit (Megatron "
                        "column/row rules over a 'model' mesh axis; "
                        "devices are split data x model; composes with "
                        "--optimizer-sharding zero1 and "
                        "--sequence-parallel)")
    p.add_argument("--tp-overlap", action="store_true",
                   help="overlap the Megatron column-parallel matmuls with "
                        "their sequence allgather: explicit ring-ppermute "
                        "collective-matmul schedule on a sequence-sharded "
                        "residual stream (parallel/tensor.py "
                        "allgather_matmul). Requires --tensor-parallel >= 2 "
                        "with --model vit and a tp-divisible token count "
                        "(e.g. --patch-size 7). Off by default: the GSPMD "
                        "propagation path stays the reference; this path "
                        "is trajectory-equal to it")
    p.add_argument("--expert-parallel", type=int, default=1,
                   help="expert-parallel width for --model moe_mlp: expert "
                        "weights (leading num_experts dim) shard over an "
                        "'expert' mesh axis (parallel/expert.py); devices "
                        "split data x expert, expert count must divide "
                        "evenly. Composes with --optimizer-sharding zero1 "
                        "and --moe-dispatch")
    p.add_argument("--moe-aux-weight", type=float, default=0.0,
                   metavar="W",
                   help="weight of the MoE router's load-balance loss in "
                        "the training objective (models/moe.py sows it "
                        "under intermediates; top-1 routing can collapse "
                        "onto one expert without it — 0.01 is a typical "
                        "switch-transformer value). 0 (default) skips the "
                        "capture entirely; metrics always report the "
                        "cross-entropy alone")
    p.add_argument("--moe-dispatch", type=str, default="dense",
                   choices=["dense", "capacity"],
                   help="moe_mlp routing: dense = algebraic one-hot "
                        "combine (layout-exact); capacity = GShard-style "
                        "physical dispatch into per-expert buffers "
                        "bounded by the capacity factor, crossing the "
                        "expert axis via all_to_all "
                        "(parallel/moe_dispatch.py)")
    p.add_argument("--sequence-parallel", type=int, default=1,
                   help="sequence-parallel width for --model vit: the token "
                        "axis is sharded over a 'seq' mesh axis and every "
                        "block's attention runs as ring attention "
                        "(neighbor ppermute over ICI, parallel/ring.py). "
                        "Token count (28/patch)^2 must divide evenly — "
                        "e.g. --patch-size 7 gives 16 tokens")
    p.add_argument("--sequence-parallel-impl", type=str, default="ring",
                   choices=["ring", "ulysses"],
                   help="ring = blockwise online-softmax with neighbor "
                        "ppermute (parallel/ring.py); ulysses = all_to_all "
                        "head re-sharding (parallel/ulysses.py; head count "
                        "must divide by the seq width, and it does not "
                        "compose with --tensor-parallel since Ulysses "
                        "re-shards heads itself)")
    p.add_argument("--patch-size", type=int, default=4,
                   help="ViT patch size (28 must divide evenly; tokens = "
                        "(28/patch)^2)")
    p.add_argument("--remat", action="store_true",
                   help="jax.checkpoint each transformer block: recompute "
                        "block activations in backward instead of storing "
                        "them (~depth x lower activation memory for the "
                        "token axis; composes with --grad-accum and the "
                        "parallelism flags). --model vit only")
    p.add_argument("--optimizer-sharding", type=str, default="none",
                   choices=["none", "zero1", "zero3"],
                   help="zero1 = shard Adam moments over the data axis "
                        "(ZeRO-1; parallel/zero.py). Params stay "
                        "replicated, XLA turns the grad AllReduce into "
                        "ReduceScatter + AllGather. zero3 = shard params "
                        "too (FSDP-style: each host stores 1/N of the "
                        "model between steps, AllGather on use)")
    p.add_argument("--zero-overlap", action="store_true",
                   help="explicit overlapped ZeRO data plane "
                        "(parallel/zero_overlap.py): bucketized gradient "
                        "reduce-scatter fenced so each bucket's "
                        "communication can overlap the remaining "
                        "backward, owner-shard optimizer update, and "
                        "the updated-shard allgather carried across the "
                        "step boundary into the next forward. Same "
                        "state layout and numerics as the default "
                        "propagation-scheduled path (equivalence "
                        "pinned); requires --optimizer-sharding "
                        "zero1|zero3 and pure data parallelism; "
                        "composes with --grad-accum")
    p.add_argument("--zero-bucket-mb", type=float, default=4.0,
                   metavar="MB",
                   help="gradient bucket budget for --zero-overlap: "
                        "size-ordered leaves pack into buckets of at "
                        "most this many MiB; each bucket is one fenced "
                        "communication-issue group (smaller = earlier "
                        "first reduce-scatter, larger = fewer, "
                        "better-utilized collectives)")
    p.add_argument("--zero-bucket-mb-dcn", type=float, default=0.0,
                   metavar="MB",
                   help="cross-slice (DCN-tier) bucket budget for "
                        "--zero-overlap on a hierarchical mesh: the "
                        "owner shards (1/ici_size of each gradient) "
                        "all-reduce across slices in buckets of at most "
                        "this many MiB — sized independently of "
                        "--zero-bucket-mb because DCN is 10-100x slower "
                        "than ICI (bigger buckets amortize its latency). "
                        "0 (default) = same as --zero-bucket-mb; no-op "
                        "on a flat (single-slice) mesh")
    p.add_argument("--dcn-slices", type=int, default=0, metavar="N",
                   help="build the hierarchical ('dcn', 'ici') mesh over "
                        "N slices instead of the flat single-slice mesh: "
                        "batch rows shard over the composed pair, ZeRO "
                        "shards within the slice (weight-update "
                        "collectives ride ICI; only 1/ici_size owner "
                        "shards cross DCN), and model axes (TP/EP) nest "
                        "inside one slice. 0 (default) = auto: the "
                        "TPUMNIST_DCN_SLICES env (emulated slice map — "
                        "how CPU worlds and tests exercise the "
                        "hierarchy), else real device.slice_index "
                        "topology, else flat. N must divide the device "
                        "count")
    p.add_argument("--debug-nans", action="store_true",
                   help="enable jax_debug_nans: every jitted step re-runs "
                        "un-jitted on a NaN/Inf result and raises at the "
                        "producing primitive (SURVEY.md section 5: the SPMD "
                        "design removes the reference's shared-mutable-state "
                        "race class; numeric blowups are the remaining "
                        "debug target). Slow - debugging only")
    p.add_argument("--grad-accum", type=int, default=1,
                   help="gradient-accumulation micro-batches per optimizer "
                        "step: the global batch splits N ways, grads "
                        "accumulate in a scan, one Adam step applies the "
                        "exact full-batch gradient (~N x lower activation "
                        "memory)")
    p.add_argument("--trainer-mode", type=str, default="scan",
                   choices=["scan", "stepwise", "explicit"])
    p.add_argument("--feed-window", type=int, default=2,
                   help="per-batch input-plane depth for stepwise/explicit "
                        "modes: W counts the batch the step consumes "
                        "plus at most W-1 staged (host gather + sharded "
                        "device_put) beyond it. 2 (default) is classic "
                        "double buffering — batch N+1 stages on a feeder "
                        "thread while the jitted step for batch N "
                        "executes; 1 disables the feeder (staging inline "
                        "on the main thread, the strict alternation the "
                        "per-batch modes always had, bit-identical "
                        "trajectories). Multi-host worlds always run the "
                        "inline path (no cross-host array assembly off "
                        "the main thread). Scan mode ignores this: its "
                        "epoch prefetch already carries host gather + H2D")
    p.add_argument("--epoch-gather", type=str, default="host",
                   choices=["host", "device"],
                   help="scan-mode batch staging: 'host' gathers each "
                        "epoch's permuted copy on the host (pipelined on "
                        "a background thread); 'device' keeps the dataset "
                        "resident on device and gathers inside the "
                        "scanned program (jnp.take) — per-epoch upload "
                        "drops from the full dataset to a ~KB index "
                        "matrix")
    p.add_argument("--checkpoint-dir", type=str, default="checkpoints")
    p.add_argument("--keep-last", type=int, default=0, metavar="N",
                   help="prune per-epoch checkpoints more than N epochs "
                        "older than the latest published one (model_best "
                        "is never pruned); 0 keeps every epoch's file, "
                        "the reference's behavior (:267-268). The window "
                        "is keyed to the latest PUBLISHED epoch so a "
                        "serve process hot-reloading from this directory "
                        "can never have its in-progress load deleted "
                        "(train/checkpoint.py ordering guarantee)")
    p.add_argument("--publish", type=str, default="full",
                   choices=["full", "delta"],
                   help="checkpoint publish format: 'full' writes the "
                        "whole npz/sharded file per epoch (default); "
                        "'delta' writes content-addressed chunks plus a "
                        "small manifest (distrib/) — adjacent epochs "
                        "share unchanged chunks, so each publish costs "
                        "O(changed bytes) and a serve fleet fetches only "
                        "what moved. Requires fully-addressable (or "
                        "replicated) leaves; sharded multi-host layouts "
                        "publish .ckpt and convert via "
                        "publish_from_checkpoint")
    p.add_argument("--chunk-mb", type=float, default=4.0, metavar="MB",
                   help="delta publish chunk budget in MiB (fixed "
                        "per-leaf byte boundaries, so a small weight "
                        "change dirties one chunk, not the file). "
                        "Default 4")
    p.add_argument("--async-checkpoint", action="store_true",
                   help="write checkpoints on a background thread, "
                        "overlapping file I/O with the next epoch "
                        "(leaves — or, for sharded multi-host layouts, "
                        "this host's owned shards — are snapshotted to "
                        "host memory first, so the saved state is exactly "
                        "the epoch's; a sharded directory is published at "
                        "the next epoch's save via a main-thread barrier, "
                        "Orbax-style deferred commit)")
    p.add_argument("--elastic", action="store_true",
                   help="survive a host loss by SHRINKING the world "
                        "instead of exiting: run the spawned world "
                        "under the elastic supervisor "
                        "(runtime/elastic.py) — on a PeerFailure the "
                        "survivors agree the shrunk membership, are "
                        "re-execed as a smaller world, and resume from "
                        "the last published checkpoint (cross-world "
                        "checkpoint resharding), with no operator "
                        "action. Requires --spawn (the supervisor owns "
                        "the worker processes; on a real pod that "
                        "actor is the cluster manager, for which "
                        "runtime/elastic.py::supervise is the "
                        "reference implementation)")
    p.add_argument("--min-world", type=int, default=1, metavar="W",
                   help="elastic floor: stop shrinking (exit code "
                        f"{elastic.EXIT_FLOOR}) when fewer than W "
                        "healthy hosts remain, instead of training on "
                        "a world this small (default 1: a single "
                        "survivor finishes the job alone)")
    p.add_argument("--elastic-grow", action="store_true",
                   help="make topology change bidirectional: each "
                        "epoch boundary runs a grow rendezvous — rank "
                        "0 checks the elastic dir for join records "
                        "(announce_join: a returned or replacement "
                        "host announcing itself), the observation is "
                        "agreed, and when joiners are pending the "
                        "generation yields so the supervisor rebuilds "
                        "it LARGER, resumed from the last published "
                        "checkpoint (the (W, W') reshard matrix "
                        "already covers W' > W). Without this flag "
                        "joiners are still admitted whenever a failure "
                        "rebuild happens anyway. Requires --elastic")
    p.add_argument("--max-world", type=int, default=0, metavar="W",
                   help="elastic ceiling for the grow direction: never "
                        "admit joiners past W total hosts (their join "
                        "records stay pending); 0 (default) = "
                        "unbounded. Requires --elastic")
    p.add_argument("--agreement-timeout", type=float, default=None,
                   metavar="SECONDS",
                   help="watchdog deadline for every multi-host agreement "
                        "collective (checkpoint prepare/write/publish "
                        "agreements, resume broadcast/agreement, dataset "
                        "agreement): a peer that dies outside an agreed "
                        "phase no longer strands this host forever — the "
                        "watchdog dumps a per-host phase report and exits "
                        "with PeerFailure naming the silent host(s). "
                        "Default: the TPUMNIST_AGREEMENT_TIMEOUT env var, "
                        "else 0 = disabled (the safe default on real "
                        "multi-host TPU, where a conservatively-sized "
                        "deadline is a new way to shoot a healthy-but-"
                        "slow job); the test harness and the chaos twins "
                        "(tools/chaos.py) turn it on")
    p.add_argument("--profile-dir", type=str, default=None,
                   help="write a jax.profiler trace here")
    p.add_argument("--compile-cache", type=str, default=None, metavar="DIR",
                   help="persistent XLA compilation cache directory: "
                        "repeat runs reuse compiled programs instead of "
                        "recompiling (~20-40s per program on TPU) — most "
                        "of the wall-clock of a short convergence run is "
                        "compile time, so this is the restart-latency "
                        "lever for --resume auto workflows. Default: the "
                        "TPUMNIST_COMPILE_CACHE env var, else "
                        "<repo>/.xla_cache (shared with bench.py and the "
                        "watcher's pre-warm). Pass an empty string to "
                        "disable caching entirely")
    p.add_argument("--no-precompile", action="store_true",
                   help="skip the AOT precompile: by default every program "
                        "the run will execute (train epoch/step, eval "
                        "twin) is .lower().compile()-d on background "
                        "threads WHILE the first epoch's host staging "
                        "runs, instead of serially at first use — the "
                        "cold-start lever (VERDICT r5: compile time is "
                        "the whole 62.4s-vs-60s north-star gap). This "
                        "flag restores lazy first-use compilation "
                        "(debugging, or measuring the unoverlapped cost)")
    p.add_argument("--metrics-file", type=str, default=None,
                   help="append one JSON line per epoch (process 0 only): "
                        "epoch, losses, accuracies, lr, images/sec — the "
                        "optional metrics file SURVEY.md section 5 notes "
                        "the reference lacks (prints only, :238-242)")
    p.add_argument("--synthetic-train-size", type=int, default=60000)
    p.add_argument("--synthetic-test-size", type=int, default=10000)
    return p


def _vit_num_heads() -> int:
    from pytorch_distributed_mnist_tpu.models.registry import (
        model_field_default,
    )

    return model_field_default("vit", "num_heads")


def _moe_num_experts() -> int:
    from pytorch_distributed_mnist_tpu.models.registry import (
        model_field_default,
    )

    return model_field_default("moe_mlp", "num_experts")


def _build_loaders(args, seed: int, mesh):
    supervision.set_phase("data_stage")
    supervision.maybe_fault("data_stage")
    name = "mnist" if args.dataset == "synthetic" else args.dataset
    synthesize = args.dataset == "synthetic"
    # Default False for programmatic callers that build args by hand.
    allow_synthetic = getattr(args, "allow_synthetic", False)

    if args.download and not synthesize:
        # Every process attempts the (idempotent, atomically-published)
        # download — correct whether hosts share a filesystem or have their
        # own.
        from pytorch_distributed_mnist_tpu.data.download import (
            download_dataset,
        )

        try:
            download_dataset(args.root, name)
        except supervision.InjectedFault:
            # The chaos harness targets the download_fetch point to
            # exercise the host-local-failure path — absorbing it here
            # would neuter the injection whenever files are already on
            # disk.
            raise
        except Exception as exc:
            # Broad on purpose (tpumnist-lint agreement-except-breadth):
            # this is a warn-and-continue path, and the real-vs-synthetic
            # outcome is agreed below on actual LOAD success — so ANY
            # download failure class (zlib.error included) must fall
            # through to that agreement, not kill this host alone.
            log0(f"WARNING: download of {name!r} failed: {exc}")

    preloaded = None
    if not synthesize and process_count() > 1:
        # The real-vs-synthetic outcome is AGREED across hosts whether or
        # not --download ran: unless every host can read the files, every
        # host takes the SAME exit — fail fast together (no
        # --allow-synthetic) or fall back to synthetic together. Deciding
        # per host inside load_split (the pre-round-5 behavior) would let
        # one host train on real rows while another trains on fake ones
        # (silent cross-host data divergence), or raise SystemExit on one
        # host while its peers hang at the next collective. The agreement
        # is on actual LOAD SUCCESS, not a dataset_present() check — a
        # presence probe leaves a window between check and read in which
        # one host's files can vanish (round-5 review), and on success the
        # loaded arrays are kept, so nothing is read twice. The agreement
        # rides the supervision record channel, so it is watchdogged and
        # a peer's poison pill from another phase parses cleanly here.
        def _try_load(train: bool):
            try:
                return load_dataset(args.root, name, train=train,
                                    synthesize_if_missing=False)
            except Exception as exc:
                # except Exception, NOT a tuple: ANY local load failure
                # — missing, corrupt ("not an IDX file" / count-mismatch
                # ValueErrors), truncated gzip (EOFError/OSError), or a
                # corrupt MID-stream gzip (zlib.error is NOT an OSError
                # subclass; round-5 advisor) — must reach the allgather
                # below, or this host dies alone while its peers block
                # forever in the timeout-less collective. Enumerated
                # tuples here are exactly the strand class the
                # agreement-except-breadth checker exists to catch.
                # Say WHICH host failed and why (every process, not
                # log0): the joint message below can only report "not
                # present".
                split = "train" if train else "test"
                print(
                    f"process {process_index()}: failed to load {name} "
                    f"{split} split: {exc!r}",
                    file=sys.stderr, flush=True,
                )
                return None

        loaded = (_try_load(train=True), _try_load(train=False))
        ok = all(split is not None for split in loaded)
        records = supervision.allgather_records(
            "dataset_load", ok, "" if ok else f"{name} load failed")
        supervision.raise_if_poisoned(records, "the dataset agreement")
        n_ok = sum(1 for rec in records if rec.ok)
        if n_ok == len(records):
            preloaded = loaded
        else:
            if not allow_synthetic:
                hint = ("the download may have failed (see any warning "
                        "above)" if args.download else
                        "pre-download on every host, or pass --download")
                exc = SystemExit(
                    f"{name!r} is not present on every host "
                    f"({n_ok}/{len(records)} loaded it) "
                    f"— {hint}, or pass --allow-synthetic to train on "
                    f"labelled fake data, or --dataset synthetic."
                )
                supervision.mark_agreed(exc)  # symmetric exit, agreed vote
                raise exc
            log0(
                f"WARNING: {name!r} is not present on every host "
                f"({n_ok}/{len(records)} loaded it); "
                "all hosts will use the synthetic fallback so training "
                "data stays consistent across the job"
            )
            synthesize = True
            name = "mnist"

    used_synthetic = synthesize

    def load_split(train: bool):
        nonlocal used_synthetic
        n = args.synthetic_train_size if train else args.synthetic_test_size
        if not synthesize:
            try:
                return load_dataset(args.root, name, train=train,
                                    synthesize_if_missing=False)
            except FileNotFoundError:
                split = "train" if train else "test"
                # Fail-fast contract (reference :137-138 always downloads
                # a missing dataset): a user reproducing the reference's
                # command line must never silently train on fake data
                # and report a fake accuracy.
                if not allow_synthetic:
                    hint = ("the download may have failed (see the "
                            "warning above)" if args.download else
                            "pass --download to fetch it")
                    raise SystemExit(
                        f"no {name} {split}-split IDX files under "
                        f"{args.root!r} — {hint}, or pass "
                        f"--allow-synthetic to train on labelled fake "
                        f"data, or --dataset synthetic."
                    )
                log0(f"WARNING: no {name} {split}-split IDX files under "
                     f"{args.root!r}; using the synthetic fallback dataset")
                used_synthetic = True
        return load_dataset(args.root, name, train=train,
                            synthetic_train_size=n, synthetic_test_size=n,
                            seed=seed)

    if preloaded is not None:
        (train_images, train_labels), (test_images, test_labels) = preloaded
    else:
        train_images, train_labels = load_split(train=True)
        test_images, test_labels = load_split(train=False)
    # Batch rows shard over the mesh's DATA axis, not over processes: a
    # host whose devices share a data coordinate with another host's
    # (multi-host TP/PP/SP — the model/stage/seq axis spans processes)
    # must feed IDENTICAL rows, or make_array_from_process_local_data
    # assembles a "replicated" batch whose replicas silently disagree.
    # Pure DP degenerates to (process_count, process_index) exactly.
    nproc, pid = data_replica_coords(mesh)
    train_loader = MNISTDataLoader(
        normalize_images(train_images, workers=args.workers), train_labels,
        batch_size=args.batch_size, train=True,
        num_replicas=nproc, rank=pid, seed=seed, workers=args.workers,
    )
    test_loader = MNISTDataLoader(
        normalize_images(test_images, workers=args.workers), test_labels,
        batch_size=args.batch_size, train=False,
        num_replicas=nproc, rank=pid, seed=seed, workers=args.workers,
        shard=nproc > 1,
    )
    return train_loader, test_loader, used_synthetic


def _resolve_resume_auto(args) -> str:
    """Resolve ``--resume auto`` to one agreed checkpoint path ('' = none).

    Every host must resume from the SAME checkpoint: a stale NFS
    attribute cache can show different listings to different hosts, and
    hosts resuming at different epochs run different numbers of
    collective programs — a silent hang, not an error. ONLY process 0
    resolves (its resolution wins anyway, and a local resolution failure
    on another host must not kill that host before the collective —
    peers would block in it forever); its record carries an ok/error
    status so a process-0 failure exits every host identically instead
    of process 0 raising alone.

    The exchange rides the supervision record channel (one fixed-width
    allgather, process 0's record is the resolution — a broadcast in
    allgather clothing): it is watchdogged like every agreement, and a
    peer that died on a host-local error pairs its poison pill with THIS
    collective and is attributed correctly instead of hanging the job.
    """
    from pytorch_distributed_mnist_tpu.train.checkpoint import (
        latest_checkpoint,
    )

    if process_count() <= 1:
        return latest_checkpoint(args.checkpoint_dir) or ""
    detail = ""
    err: Optional[str] = None
    if process_index() == 0:
        try:
            resolved = latest_checkpoint(args.checkpoint_dir) or ""
            encoded = resolved.encode()
            if len(encoded) > supervision.DETAIL_BYTES:
                raise ValueError(
                    f"checkpoint path is {len(encoded)} bytes, over the "
                    f"{supervision.DETAIL_BYTES}-byte resume-resolution "
                    "record budget; use a shorter --checkpoint-dir"
                )
            detail = resolved
        except Exception as exc:  # noqa: BLE001 - agreed below
            err = repr(exc)
    records = supervision.allgather_records(
        "resume_resolve", err is None, detail if err is None else err)
    supervision.raise_if_poisoned(records, "resume resolution")
    leader = records[0]
    if not leader.ok:
        exc = SystemExit(
            "--resume auto: resolution failed on process 0: "
            + leader.detail
        )
        # Every host leaves this agreement raising this same exit; mark
        # it so nobody sends a poison pill no peer would pair with.
        supervision.mark_agreed(exc)
        raise exc
    return leader.detail


def _note_cross_world_resume(resume_path: str) -> None:
    """Meta-only inspection before the resume load: when the checkpoint
    was saved by a DIFFERENT world (the elastic shrink/grow paths, or
    any relaunch at a new topology), say so up front — the restore is a
    deliberate cross-world reshard, recorded as a ``checkpoint_reshard``
    event LABELED with its direction (``grow`` when this world is
    larger than the saving one — lexicographic on (processes, devices),
    the order resharding cost follows — ``shrink`` when smaller), so
    the metrics JSONL tells the two elastic directions apart without
    diffing member lists. Not a surprise to reconstruct from a failed
    load. Best-effort on purpose: unreadable meta is left for the load
    itself to classify (corruption vs mismatch), pre-stamp checkpoints
    carry no provenance.
    """
    from pytorch_distributed_mnist_tpu.train.checkpoint import (
        checkpoint_world,
    )

    try:
        saved = checkpoint_world(resume_path)
    except Exception:  # noqa: BLE001 - the load will classify the damage
        return
    if not saved:
        return
    current = {"processes": process_count(),
               "devices": jax.device_count()}
    if saved != current:
        # The worlds differ, and both dicts hold exactly (processes,
        # devices), so the tuple comparison is a strict two-way split.
        if (current["processes"], current["devices"]) \
                > (saved["processes"], saved["devices"]):
            direction = "grow"
        else:
            direction = "shrink"
        failure_events.record(
            "checkpoint_reshard",
            f"{resume_path}: saved by a {saved['processes']}-process/"
            f"{saved['devices']}-device world; resharding onto this "
            f"{current['processes']}-process/{current['devices']}-device "
            f"world ({direction})", saved=saved, current=current,
            direction=direction)
        log0(f"=> checkpoint '{resume_path}' was saved at world "
             f"{saved['processes']}x{saved['devices']} (processes x "
             f"devices); resharding onto {current['processes']}x"
             f"{current['devices']} ({direction})")


def _resume_supervised(args, state):
    """Resolve + load the resume checkpoint under the agreement protocol.

    Returns ``(state, start_epoch, best_acc, resume_path)``. Semantics:

    - Agree the per-host load OUTCOME, not just the path: a stale NFS
      attribute cache can hide the agreed checkpoint from one host —
      ``try_resume`` would then silently train fresh at epoch 0 while
      its peers resume at N, so hosts run different numbers of
      collective programs (a silent hang). All hosts proceed at the same
      epoch, or all exit loudly with the same error.
    - Corrupt-checkpoint resilience (``--resume auto`` only): when the
      resolved latest checkpoint is damaged — truncated write the crash
      left behind, torn download — on EVERY host, it is quarantined
      (renamed ``*.corrupt``, invisible to resolution) and resolution
      falls back to the next-older epoch through the same agreement
      path, instead of aborting a run that has perfectly good older
      checkpoints. A load failure that is NOT corruption (model/shape
      mismatch), or one that differs across hosts, still aborts loudly:
      quarantining a good checkpoint because one host's NFS view is
      stale would destroy training history.
    """
    supervision.set_phase("resume")
    supervision.maybe_fault("resume")
    auto = args.resume == "auto"
    multi = process_count() > 1
    while True:
        if auto:
            resume_path = _resolve_resume_auto(args)
            if not resume_path:
                log0(f"=> --resume auto: no checkpoint in "
                     f"'{args.checkpoint_dir}' yet, training fresh")
                return state, 0, 0.0, ""
        else:
            resume_path = args.resume
        if resume_path and (os.path.isfile(resume_path)
                            or os.path.isdir(resume_path)):
            _note_cross_world_resume(resume_path)
        if not (multi and resume_path):
            try:
                new_state, start_epoch, best_acc = try_resume(
                    resume_path, state)
            except Exception as exc:
                if auto and is_corrupt_checkpoint_error(exc):
                    dest = quarantine_checkpoint(resume_path)
                    failure_events.record(
                        "checkpoint_quarantined",
                        f"{resume_path} -> {dest}: {exc!r}")
                    log0(f"=> quarantined corrupt checkpoint "
                         f"{resume_path!r} -> {dest!r} ({exc!r}); "
                         f"falling back to the next-older epoch")
                    continue
                raise
            return new_state, start_epoch, best_acc, resume_path

        resume_err: Optional[BaseException] = None
        corrupt = False
        new_state = state
        start_epoch, best_acc = 0, 0.0
        try:
            new_state, start_epoch, best_acc = try_resume(
                resume_path, state)
            outcome = str(start_epoch)
        except Exception as exc:  # noqa: BLE001 - agreed below
            print(
                f"process {process_index()}: resume from "
                f"{resume_path!r} failed: {exc!r}",
                file=sys.stderr, flush=True,
            )
            resume_err = exc
            corrupt = is_corrupt_checkpoint_error(exc)
            outcome = ("corrupt:" if corrupt else "error:") + repr(exc)
        records = supervision.allgather_records(
            "resume_load", resume_err is None, outcome)
        if resume_err is not None:
            supervision.mark_agreed(resume_err)  # delivered just above
        supervision.raise_if_poisoned(records, "the resume agreement")
        epochs = [int(rec.detail) if rec.ok else -1 for rec in records]
        if all(e == epochs[0] for e in epochs):
            if resume_err is None:
                return new_state, start_epoch, best_acc, resume_path
            all_corrupt = all(
                rec.detail.startswith("corrupt:")
                for rec in records if not rec.ok
            )
            if all_corrupt and auto:
                # Same damaged file everywhere (a torn write on the
                # shared filesystem): process 0 quarantines it, the
                # outcome is agreed (a rename failure aborts every host
                # together), and resolution re-runs on what's left.
                qerr: Optional[BaseException] = None
                dest = ""
                if process_index() == 0:
                    try:
                        dest = quarantine_checkpoint(resume_path)
                    except Exception as exc:  # noqa: BLE001
                        qerr = exc
                failed = supervision.agree("resume_quarantine", qerr)
                if failed and qerr is None:
                    raise supervision.PeerFailure(
                        supervision.peer_failure_message(
                            failed,
                            f"quarantine of corrupt checkpoint "
                            f"{resume_path!r} failed on host(s) "
                            f"{[h for h, _, _ in failed]};",
                        ),
                        hosts=[h for h, _, _ in failed],
                        phase="resume_quarantine",
                        reason=failed[0][2],
                    )
                if qerr is not None:
                    raise qerr
                failure_events.record(
                    "checkpoint_quarantined",
                    f"{resume_path} -> {dest or '(renamed on process 0)'}"
                    f": {resume_err!r}")
                log0(f"=> quarantined corrupt checkpoint "
                     f"{resume_path!r} ({resume_err!r}); falling back "
                     f"to the next-older epoch")
                continue
            raise resume_err  # identical on every host (agreed above)
        exc = SystemExit(
            f"resume outcome diverged across hosts for "
            f"{resume_path!r}: start epochs {epochs} "
            f"(-1 = load failed). A host resuming at a different "
            f"epoch runs different collective programs — a silent "
            f"hang, not an error. Check that --checkpoint-dir is a "
            f"filesystem shared by all hosts and the checkpoint is "
            f"intact on every host."
        )
        supervision.mark_agreed(exc)  # symmetric exit on every host
        raise exc


def run(args, epoch_callback=None) -> dict:
    """Per-process SPMD lifecycle; returns a summary dict for tests/benchmarks.

    ``epoch_callback(epoch, history_row) -> bool`` (optional) fires after
    each epoch's train+eval+checkpoint; returning True stops the loop early
    (tools/northstar.py uses this to stop at the target accuracy).

    The whole body runs under the agreed-exit protocol
    (``runtime/supervision.py``): ANY host-local failure — data staging,
    step execution, checkpoint collect/write, eval — delivers a
    poison-pill record to the next agreement collective before this host
    unwinds, so peers exit with ``PeerFailure(host, phase, reason)``
    instead of blocking forever in a timeout-less collective.
    """
    try:
        return _run_body(args, epoch_callback)
    except BaseException as exc:
        # deliver_poison is a no-op for single-process runs, for
        # KeyboardInterrupt, for already-agreed failures (PeerFailure /
        # watchdog aborts), and when the saver's __exit__ already sent
        # the pill for this exception (idempotent per exception).
        # write_survivor_record is the elastic runtime's membership
        # vote (runtime/elastic.py): under an elastic supervisor, a
        # PeerFailure/transport unwind serializes this host's survival
        # and the dead set before exit, so the supervisor can rebuild
        # the shrunk world; a no-op everywhere else. It runs FIRST —
        # local file I/O, sub-second — because a transport-shaped raw
        # error would otherwise sit in deliver_poison's bounded (but up
        # to 60s) undeliverable-pill attempt while the supervisor's
        # settle deadline counts this healthy host toward the dead.
        # escalate_exit arms a hard-exit timer ONLY for peer-failure
        # deaths, whose interpreter teardown would otherwise hang in the
        # distributed shutdown barrier the dead peers can never join.
        elastic.write_survivor_record(exc)
        supervision.deliver_poison(exc)
        supervision.escalate_exit(exc)
        raise


def _run_body(args, epoch_callback=None) -> dict:
    # An explicit JAX_PLATFORMS=cpu request (spawned children, smoke tests)
    # must win even when an accelerator plugin force-writes jax_platforms at
    # import time; tests/conftest.py and tools/northstar.py apply the same
    # override for their own processes.
    import os as _os0

    if _os0.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    # Must run before ANY jax call that initializes the backend (including
    # jax.process_index in log0) — jax.distributed.initialize refuses to run
    # after backend init, the analog of init_process_group-before-CUDA order.
    initialize_distributed(args.coordinator, args.num_processes, args.process_id)
    # run() is re-entrant within one process (tests, benchmarks) and the
    # flag is process-global, so a previous debug run must not leak
    # NaN-trapping into a later run that didn't ask for it — but a user's
    # own JAX_DEBUG_NANS env (the standard JAX switch, honored at import)
    # must not be clobbered by the flag's default either.
    import os as _os

    debug_nans = bool(getattr(args, "debug_nans", False)) or bool(
        _os.environ.get("JAX_DEBUG_NANS")
    )
    jax.config.update("jax_debug_nans", debug_nans)
    # Persistent compile cache: the SHARED wiring (utils/compile_cache.py)
    # used by every entry point — bench.py, tools/northstar.py, the test
    # harness, and this run(). Resolution: --compile-cache flag >
    # TPUMNIST_COMPILE_CACHE env > harness-pinned ambient config >
    # <repo>/.xla_cache default; flag/env "" disables. Re-entrant-safe:
    # a previous run()'s dir never leaks into a run that asked otherwise.
    if process_count() > 1 and jax.devices()[0].platform == "cpu":
        # Persistent-cache reads are FATAL in a multi-process CPU (gloo
        # collectives) world on this jaxlib: deserializing a cached
        # executable — including multihost_utils' own allgather program —
        # aborts the process (SIGSEGV/SIGABRT, reproduced in the chaos
        # twins; sibling hazard to the in-process read-after-write heap
        # corruption in docs/DESIGN.md). The local pod simulation
        # therefore runs uncached; real TPU pods keep the cache.
        cache_dir = compile_cache.configure("")
        log0("compile cache: disabled (multi-process CPU backend — "
             "cached-executable reads abort on this jaxlib)")
    else:
        cache_dir = compile_cache.configure(
            getattr(args, "compile_cache", None))
    if cache_dir:
        log0(f"compile cache: {cache_dir}")
    # Run supervision: agreement watchdogs (--agreement-timeout flag >
    # TPUMNIST_AGREEMENT_TIMEOUT env > 0 = off), fault-plan parse
    # (TPUMNIST_FAULT, the chaos harness), and a fresh failure-event log.
    # Re-entrant-safe for the same reason as the cache wiring above.
    agreement_timeout = supervision.configure(
        getattr(args, "agreement_timeout", None))
    failure_events.reset()
    # The shared JSONL sink (utils/profiling.py): per-epoch metric rows,
    # supervision/failure events, and — in a serve process sharing the
    # flag — serving stats all append to ONE file in one format. Attached
    # directly after the reset so even resume-time events (checkpoint
    # quarantines) reach the stream.
    metrics_sink = None
    metrics_file = getattr(args, "metrics_file", None)
    if metrics_file and process_index() == 0:
        from pytorch_distributed_mnist_tpu.utils.profiling import JsonlSink

        metrics_sink = JsonlSink(metrics_file)
        failure_events.set_sink(metrics_sink, source="train")
    if agreement_timeout:
        log0(f"agreement watchdog: {agreement_timeout:g}s deadline")
    # Elastic rebuild provenance: when this process is the first
    # generation after a shrink, record the world_shrunk event (old/new
    # membership) — after the reset + sink attach above, so it reaches
    # both the run summary and the metrics JSONL.
    elastic.note_rebuilt_world()
    log0(args)  # startup args print parity (:337)
    seed = args.seed if args.seed is not None else 0
    if args.seed is not None:
        random.seed(args.seed)
        np.random.seed(args.seed)

    pp = getattr(args, "pipeline_stages", 1)
    tp = getattr(args, "tensor_parallel", 1)
    sp = getattr(args, "sequence_parallel", 1)
    ep = getattr(args, "expert_parallel", 1)
    patch = getattr(args, "patch_size", 4)
    grad_accum = getattr(args, "grad_accum", 1)
    tp_overlap = getattr(args, "tp_overlap", False)
    if tp_overlap and (tp < 2 or pp > 1):
        raise SystemExit(
            "--tp-overlap requires --tensor-parallel >= 2 without "
            "--pipeline-stages (it rewrites the pure DP x TP schedule; "
            "the pipeline's stage body is already an explicit program)"
        )
    if ep > 1:
        # EP targets the MoE family; TP/SP/PP target the ViT. The mesh
        # families are disjoint (data x expert vs data x model/seq/stage),
        # so the combinations are rejected at flag level, not discovered
        # as a sharding trace error.
        if tp > 1 or sp > 1 or pp > 1:
            raise SystemExit(
                "--expert-parallel does not combine with "
                "--tensor-parallel/--sequence-parallel/--pipeline-stages: "
                "EP shards the moe_mlp expert dim over a data x expert "
                "mesh; the others shard the ViT"
            )
        if args.model != "moe_mlp":
            raise SystemExit(
                f"--expert-parallel requires --model moe_mlp (the EP rule "
                f"table shards the leading num_experts weight dim; other "
                f"models would silently stay replicated); got --model "
                f"{args.model}"
            )
        if args.trainer_mode == "explicit":
            raise SystemExit(
                "--expert-parallel does not compose with --trainer-mode "
                "explicit (the explicit shard_map owns the whole mesh as "
                "a data axis); use scan or stepwise"
            )
        num_experts = _moe_num_experts()
        if num_experts % ep:
            raise SystemExit(
                f"--expert-parallel {ep} must divide the moe_mlp's "
                f"{num_experts} experts"
            )
        if jax.device_count() % ep:
            raise SystemExit(
                f"--expert-parallel {ep} does not divide the "
                f"{jax.device_count()} available devices"
            )
    if getattr(args, "optimizer_sharding", "none") == "zero3" \
            and (tp > 1 or sp > 1 or ep > 1):
        # ZeRO-3 composes with plain DP (and is separately rejected under
        # PP below): stacking param-sharding on top of a TP/SP/EP rule
        # table is an untested layout the composition matrix (README)
        # marks unsupported — reject it at flag level rather than let an
        # undocumented composition run.
        raise SystemExit(
            "--optimizer-sharding zero3 composes with data parallelism "
            "only; combine TP/SP/EP with zero1 instead (README "
            "composition matrix)"
        )
    if patch < 1 or 28 % patch:
        raise SystemExit(
            f"--patch-size {patch}: 28 must divide evenly into patches "
            f"(try 2, 4, 7, or 14)"
        )
    if grad_accum < 1:
        raise SystemExit(f"--grad-accum must be >= 1, got {grad_accum}")
    if grad_accum > 1:
        if args.trainer_mode == "explicit":
            raise SystemExit(
                "--grad-accum does not compose with --trainer-mode "
                "explicit; use scan or stepwise"
            )
        if args.batch_size % grad_accum:
            raise SystemExit(
                f"--grad-accum {grad_accum} must divide --batch-size "
                f"{args.batch_size}"
            )
        if pp > 1:
            # Each accumulation micro-batch feeds the pipeline, which
            # divides it again: per-dataslice size must still split into
            # the pipeline's own microbatches (== stages by default).
            dp_size = max(1, jax.device_count() // pp)
            per_slice = args.batch_size // grad_accum // dp_size
            if (args.batch_size // grad_accum) % dp_size or per_slice % pp:
                raise SystemExit(
                    f"--grad-accum {grad_accum} with --pipeline-stages "
                    f"{pp}: each accumulation micro-batch "
                    f"({args.batch_size // grad_accum}) must split over "
                    f"{dp_size} data slices into a per-slice batch "
                    f"divisible by {pp} pipeline microbatches"
                )
    if ep > 1 and getattr(args, "moe_dispatch", "dense") == "capacity" \
            and (args.batch_size // grad_accum) % jax.device_count():
        # After the grad-accum divisibility checks above, so the per-step
        # batch in this message is exact. The capacity dispatch
        # shard_maps tokens over every mesh axis (data x expert groups);
        # shard_map needs exact divisibility — fail with flag language,
        # not a trace error.
        raise SystemExit(
            f"--moe-dispatch capacity with --expert-parallel {ep}: "
            f"the per-step batch ({args.batch_size // grad_accum}) "
            f"must divide evenly over the {jax.device_count()} "
            f"data x expert token groups"
        )
    # Flag-level aux/gather validation lives HERE with its siblings, not
    # after mesh/model/state construction: a bad combo must be rejected
    # before minutes of expensive init (round-3 advisor finding).
    epoch_gather = getattr(args, "epoch_gather", "host")
    if epoch_gather == "device" and args.trainer_mode != "scan":
        raise SystemExit(
            "--epoch-gather device requires --trainer-mode scan (the "
            "gather lives inside the scanned epoch program)"
        )
    aux_weight = getattr(args, "moe_aux_weight", 0.0)
    if aux_weight:
        if args.model != "moe_mlp":
            raise SystemExit(
                f"--moe-aux-weight applies to --model moe_mlp (the router "
                f"sows the load-balance loss); got --model {args.model}"
            )
        if args.trainer_mode == "explicit":
            raise SystemExit(
                "--moe-aux-weight does not compose with --trainer-mode "
                "explicit; use scan or stepwise"
            )
    zero_overlap = getattr(args, "zero_overlap", False)
    zero_bucket_mb = getattr(args, "zero_bucket_mb", 4.0)
    if zero_overlap:
        # The overlapped plane is the pure-DP explicit schedule; every
        # unsupported composition is rejected with flag language here
        # (and again as ValueError in the Trainer for library callers).
        if getattr(args, "optimizer_sharding", "none") == "none":
            raise SystemExit(
                "--zero-overlap schedules the ZeRO weight update "
                "explicitly; pass --optimizer-sharding zero1 or zero3 "
                "with it"
            )
        if args.trainer_mode == "explicit":
            raise SystemExit(
                "--zero-overlap does not compose with --trainer-mode "
                "explicit (both own the whole mesh as one shard_map "
                "data axis); use scan or stepwise"
            )
        if tp > 1 or sp > 1 or ep > 1 or pp > 1:
            raise SystemExit(
                "--zero-overlap composes with data parallelism only; "
                "TP/SP/EP/PP layouts stay on the default "
                "propagation-scheduled path (drop --zero-overlap)"
            )
        if aux_weight:
            raise SystemExit(
                "--zero-overlap does not compose with --moe-aux-weight "
                "(the sown aux statistic is a global-batch quantity; "
                "the overlapped body sees local shards)"
            )
        if getattr(args, "loss", "xla") == "fused":
            raise SystemExit(
                "--zero-overlap does not compose with --loss fused "
                "(the fused kernel's shard_map cannot nest inside the "
                "overlapped step's shard_map over the same data axis)"
            )
        if epoch_gather == "device":
            raise SystemExit(
                "--zero-overlap requires --epoch-gather host (the "
                "overlapped step is not embedded in the device-gather "
                "epoch program)"
            )
        if zero_bucket_mb <= 0:
            raise SystemExit(
                f"--zero-bucket-mb must be > 0, got {zero_bucket_mb:g}"
            )
    zero_bucket_mb_dcn = getattr(args, "zero_bucket_mb_dcn", 0.0)
    if zero_bucket_mb_dcn < 0:
        raise SystemExit(
            f"--zero-bucket-mb-dcn must be >= 0 (0 = same as "
            f"--zero-bucket-mb), got {zero_bucket_mb_dcn:g}"
        )
    if zero_bucket_mb_dcn and not zero_overlap:
        raise SystemExit(
            "--zero-bucket-mb-dcn sizes the --zero-overlap schedule's "
            "cross-slice buckets; pass --zero-overlap (and a "
            "hierarchical mesh via --dcn-slices) with it"
        )
    # Hierarchical (DCN x ICI) mesh resolution: flag > TPUMNIST_DCN_SLICES
    # env > real device.slice_index topology > flat. Validated here with
    # flag language, BEFORE model/state construction.
    from pytorch_distributed_mnist_tpu.parallel.mesh import (
        infer_dcn_slices,
        make_hier_mesh,
        validate_dcn_slices,
    )

    dcn = getattr(args, "dcn_slices", 0) or 0
    if dcn < 0:
        raise SystemExit(f"--dcn-slices must be >= 0, got {dcn}")
    if not dcn:
        try:
            dcn = infer_dcn_slices()
        except ValueError as exc:
            raise SystemExit(str(exc))
    if dcn > 1:
        # The FULL slice-topology validation (count divisibility AND,
        # on real multi-slice hardware, slice-count match and equal
        # sizes) — the same checks make_hier_mesh runs, so the later
        # construction cannot fail for slice reasons.
        try:
            validate_dcn_slices(dcn)
        except ValueError as exc:
            if elastic.generation() > 0:
                # An elastic rebuild (slice loss) can leave a world the
                # configured slice count no longer fits — e.g. the
                # surviving slice alone. Landing FLAT there is the
                # designed outcome (the reshard matrix covers the
                # layout change); aborting would turn a survived slice
                # loss into an outage.
                failure_events.record(
                    "dcn_flat_fallback",
                    f"{dcn} DCN slices no longer fit the rebuilt "
                    f"{jax.device_count()}-device world ({exc}); "
                    f"continuing on the flat mesh")
                log0(f"=> elastic rebuild: {dcn} DCN slices do not fit "
                     f"the surviving {jax.device_count()}-device world "
                     f"({exc}); continuing on the flat mesh")
                dcn = 1
            else:
                raise SystemExit(f"--dcn-slices {dcn}: {exc}")
    if dcn > 1:
        # The paths that own the mesh's data axis BY NAME inside a
        # shard_map (ring/Ulysses attention, the GPipe stage program,
        # the explicit-DP step, the fused loss kernel, the capacity
        # dispatch) predate the composed ('dcn', 'ici') axis; each is
        # rejected with flag language rather than discovered as a trace
        # error. TP/EP rule tables are pure GSPMD shardings and compose
        # — pinned to the ICI tier by make_hier_mesh.
        if pp > 1:
            raise SystemExit(
                "--dcn-slices does not compose with --pipeline-stages "
                "(the GPipe shard_map owns the mesh's data axis by "
                "name); pipeline stages stay on the flat single-slice "
                "mesh"
            )
        if sp > 1:
            raise SystemExit(
                "--dcn-slices does not compose with --sequence-parallel "
                "(the ring/Ulysses shard_map owns the mesh's data axis "
                "by name); sequence parallelism stays on the flat "
                "single-slice mesh"
            )
        if args.trainer_mode == "explicit":
            raise SystemExit(
                "--dcn-slices does not compose with --trainer-mode "
                "explicit (the explicit shard_map owns the whole mesh "
                "as one flat data axis); use scan or stepwise"
            )
        if getattr(args, "loss", "xla") == "fused":
            raise SystemExit(
                "--dcn-slices does not compose with --loss fused (the "
                "kernel's nested shard_map names the flat data axis); "
                "use the default --loss xla"
            )
        if ep > 1 and getattr(args, "moe_dispatch", "dense") == "capacity":
            raise SystemExit(
                "--dcn-slices does not compose with --moe-dispatch "
                "capacity (the dispatch shard_map crosses every mesh "
                "axis by name); use --moe-dispatch dense"
            )
        if tp > 1 and getattr(args, "attention", "dense") == "flash":
            raise SystemExit(
                "--dcn-slices with --tensor-parallel does not compose "
                "with --attention flash (the kernel's shard_map names "
                "the flat data axis); use --attention dense"
            )
        per_slice = jax.device_count() // dcn
        model_width = tp * sp * ep
        if per_slice % model_width:
            raise SystemExit(
                f"model parallelism (width {model_width}) would "
                f"straddle the DCN boundary: --dcn-slices {dcn} leaves "
                f"{per_slice} chip(s) per slice, and TP/EP groups must "
                f"nest inside one slice's ICI domain (every layer "
                f"collective would otherwise ride the 10-100x slower "
                f"cross-slice axis)"
            )
    if pp > 1 and sp > 1:
        raise SystemExit(
            "--pipeline-stages does not compose with --sequence-parallel: "
            "the ring/Ulysses attention is itself a shard_map collective "
            "program and cannot nest inside the pipeline's shard_map body "
            "(see docs/DESIGN.md for the cost argument)"
        )
    if pp > 1:
        if args.model != "vit":
            raise SystemExit(
                f"--pipeline-stages requires --model vit (the pipelined "
                f"architecture is embed -> N transformer blocks -> head); "
                f"got --model {args.model}"
            )
        if getattr(args, "optimizer_sharding", "none") == "zero3":
            raise SystemExit(
                "--pipeline-stages composes with --optimizer-sharding "
                "zero1 (moments sharded stage x data); zero3 would "
                "re-shard the stage-sharded params themselves (see "
                "docs/DESIGN.md)"
            )
        if jax.device_count() % (pp * tp):
            raise SystemExit(
                f"--pipeline-stages {pp}"
                + (f" x --tensor-parallel {tp}" if tp > 1 else "")
                + f" does not divide the {jax.device_count()} available "
                  f"devices"
            )
        if tp > 1:
            num_heads = _vit_num_heads()
            if num_heads % tp:
                raise SystemExit(
                    f"--tensor-parallel {tp} with --pipeline-stages: the "
                    f"Megatron stage body shards the ViT's {num_heads} "
                    f"attention heads over the model axis, so the width "
                    f"must divide {num_heads}"
                )
            # PP x TP: data x stage x model mesh; the stage body runs the
            # explicit-Megatron block (parallel/pipeline_tp.py) since
            # GSPMD cannot propagate inside the pipeline's shard_map.
            mesh = make_mesh(
                ("data", "stage", "model"),
                shape=(jax.device_count() // (pp * tp), pp, tp))
        else:
            mesh = make_mesh(("data", "stage"),
                             shape=(jax.device_count() // pp, pp))
    elif tp > 1 or sp > 1:
        if args.model != "vit":
            raise SystemExit(
                f"--tensor-parallel/--sequence-parallel require --model "
                f"vit (the Megatron rule table and the ring attention "
                f"target its blocks; other models would silently stay "
                f"replicated); got --model {args.model}"
            )
        flash_ok = (
            tp == 1 and sp > 1
            and getattr(args, "sequence_parallel_impl", "ring") == "ulysses"
        ) or (tp > 1 and sp == 1)
        if getattr(args, "attention", "dense") == "flash" and not flash_ok:
            raise SystemExit(
                "--attention flash composes with "
                "--sequence-parallel-impl ulysses (full sequence per "
                "device, head subset) or with --tensor-parallel alone "
                "(kernel shard_mapped over batch x heads); the ring "
                "supplies its own blockwise attention"
            )
        if jax.device_count() % (tp * sp):
            raise SystemExit(
                f"--tensor-parallel {tp} x --sequence-parallel {sp} does "
                f"not divide the {jax.device_count()} available devices"
            )
        if sp > 1:
            tokens = (28 // patch) ** 2
            if tokens % sp:
                raise SystemExit(
                    f"--sequence-parallel {sp} needs the token count "
                    f"(28/patch)^2 divisible by it; --patch-size {patch} "
                    f"gives {tokens} tokens — try --patch-size 7 "
                    f"(16 tokens)"
                )
            if args.trainer_mode == "explicit":
                raise SystemExit(
                    "--sequence-parallel does not compose with "
                    "--trainer-mode explicit (the ring's shard_map cannot "
                    "nest inside the explicit-DP shard_map); use scan or "
                    "stepwise"
                )
            num_heads = _vit_num_heads()
            if tp > 1 and num_heads % tp:
                raise SystemExit(
                    f"--tensor-parallel {tp} with --sequence-parallel: the "
                    f"ring shards the ViT's {num_heads} attention heads "
                    f"exactly over the model axis, so the width must "
                    f"divide {num_heads}"
                )
            sp_impl = getattr(args, "sequence_parallel_impl", "ring")
            if sp_impl == "ulysses":
                if tp > 1:
                    raise SystemExit(
                        "--sequence-parallel-impl ulysses does not compose "
                        "with --tensor-parallel: Ulysses re-shards the "
                        "head axis itself (all_to_all)"
                    )
                if num_heads % sp:
                    raise SystemExit(
                        f"--sequence-parallel-impl ulysses shards the "
                        f"{num_heads} heads over the seq axis; "
                        f"--sequence-parallel {sp} must divide {num_heads}"
                    )
        if tp_overlap:
            # The overlapped schedule owns the sequence axis (it shards
            # tokens over 'model' between blocks) and runs in its own
            # shard_map — every composition that would contend for either
            # is rejected at flag level.
            if sp > 1:
                raise SystemExit(
                    "--tp-overlap does not compose with "
                    "--sequence-parallel: the overlapped schedule already "
                    "shards the token axis (over 'model', between blocks)"
                )
            tokens = (28 // patch) ** 2
            if tokens % tp:
                raise SystemExit(
                    f"--tp-overlap shards the ViT's {tokens} tokens over "
                    f"--tensor-parallel {tp}, which does not divide "
                    f"evenly; try --patch-size 7 (16 tokens)"
                )
            if args.trainer_mode == "explicit":
                raise SystemExit(
                    "--tp-overlap does not compose with --trainer-mode "
                    "explicit (the overlapped shard_map cannot nest "
                    "inside the explicit-DP shard_map); use scan or "
                    "stepwise"
                )
            if getattr(args, "attention", "dense") == "flash":
                raise SystemExit(
                    "--tp-overlap hands attention this device's local "
                    "heads directly inside its shard_map; --attention "
                    "flash's GSPMD wrapper does not apply there"
                )
            if getattr(args, "optimizer_sharding", "none") != "none":
                raise SystemExit(
                    "--tp-overlap uses the explicit head-major layout "
                    "(parallel/pipeline_tp.py); the ZeRO rule composition "
                    "targets the standard flax tree — drop "
                    "--optimizer-sharding"
                )
        # sp > 1 with dcn > 1 was rejected above, so the hierarchical
        # branch only ever carries the (GSPMD-pure) model axis.
        if dcn > 1:
            mesh = make_hier_mesh(dcn, extra_axes=("model", "seq"),
                                  extra_shape=(tp, sp))
        else:
            mesh = make_mesh(("data", "model", "seq"),
                             shape=(jax.device_count() // (tp * sp), tp, sp))
    elif ep > 1:
        if dcn > 1:
            mesh = make_hier_mesh(dcn, extra_axes=("expert",),
                                  extra_shape=(ep,))
        else:
            mesh = make_mesh(("data", "expert"),
                             shape=(jax.device_count() // ep, ep))
    elif dcn > 1:
        mesh = make_hier_mesh(dcn)
    else:
        mesh = make_mesh(("data",))
    log0(f"devices: {jax.device_count()} ({jax.devices()[0].platform}), "
         f"processes: {process_count()}, mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))}")
    if dcn > 1:
        from pytorch_distributed_mnist_tpu.parallel.mesh import (
            device_slice_index,
        )

        emulated = any(device_slice_index(d) is None for d in jax.devices())
        log0(f"hierarchical mesh: {dcn} DCN slice(s) x "
             f"{jax.device_count() // dcn} chip(s)/slice"
             + (" (emulated slice map — host-thread collectives say "
                "nothing about real DCN latency)" if emulated else ""))
    if args.workers:
        from pytorch_distributed_mnist_tpu.data import native as _native

        if not _native.available():
            # The reference's --workers feeds real DataLoader processes
            # (:156); here the parallel host gather needs the optional
            # native lib (make -C native). Say so at startup instead of
            # silently no-op'ing the flag (round-3 VERDICT missing #3).
            log0(f"NOTE: -j/--workers {args.workers} is a no-op: the "
                 f"native loader backend is not built (make -C native); "
                 f"using the NumPy host path + prefetch thread")

    from pytorch_distributed_mnist_tpu.ops.loss import set_loss_impl

    loss_impl = getattr(args, "loss", "xla")
    if loss_impl == "fused":
        # GSPMD modes get the mesh so the kernel runs per-device on local
        # batch shards via a nested shard_map (P('data') in_specs force a
        # batch-sharded, model/seq-replicated layout — valid on TP/SP
        # meshes AND the pipeline's data x stage mesh: the logits leaving
        # the GPipe shard_map are data-sharded and stage-replicated,
        # exactly the layout the loss's in_specs request); the explicit
        # mode is already inside a shard_map (no nesting over the same
        # axis).
        set_loss_impl(
            "fused",
            mesh=mesh if args.trainer_mode != "explicit" else None,
        )
    else:
        set_loss_impl("xla")

    model_kwargs = {}
    if getattr(args, "dtype", None):
        if not model_accepts(args.model, "compute_dtype"):
            raise SystemExit(
                f"--dtype not supported: model {args.model!r} does not "
                f"accept a compute_dtype"
            )
        import jax.numpy as jnp

        model_kwargs["compute_dtype"] = {
            "bf16": jnp.bfloat16, "f32": jnp.float32,
        }[args.dtype]
    if getattr(args, "attention", "dense") == "flash":
        # Explicit capability probe (not except TypeError, which would
        # swallow genuine constructor bugs as a flag error).
        if not model_accepts(args.model, "attention_fn"):
            raise SystemExit(
                f"--attention {args.attention} not supported: model "
                f"{args.model!r} does not accept an attention_fn"
            )
        from pytorch_distributed_mnist_tpu.ops.pallas.flash import flash_attention

        model_kwargs["attention_fn"] = flash_attention
    if patch != 4:
        if not model_accepts(args.model, "patch_size"):
            raise SystemExit(
                f"--patch-size only applies to models with patches; "
                f"{args.model!r} does not accept one"
            )
        model_kwargs["patch_size"] = patch
    moe_dispatch = getattr(args, "moe_dispatch", "dense")
    if getattr(args, "remat", False):
        if not model_accepts(args.model, "remat"):
            raise SystemExit(
                f"--remat only applies to block-structured models; "
                f"{args.model!r} does not accept it"
            )
        model_kwargs["remat"] = True
    init_model = None  # a dense-attention twin when the real apply can't init
    if sp > 1:
        from functools import partial as _partial

        # Params are attention-impl-independent; init must use the dense
        # twin (the batch-1 init trace can't satisfy the SP data-axis
        # sharding), then the sequence-parallel apply_fn is swapped in —
        # the same pattern the dryrun's DP x TP x SP phase uses.
        # With --attention flash, the guard above admitted only the
        # Ulysses composition: the kernel becomes the per-device LOCAL
        # attention inside its shard_map (full sequence, local heads).
        local_attn = model_kwargs.pop("attention_fn", None)
        init_model = get_model(args.model, **model_kwargs)
        if getattr(args, "sequence_parallel_impl", "ring") == "ulysses":
            from pytorch_distributed_mnist_tpu.parallel.ulysses import (
                ulysses_attention,
            )

            model_kwargs["attention_fn"] = _partial(
                ulysses_attention, mesh=mesh, axis="seq", batch_axis="data",
                local_attention=local_attn,
            )
        else:
            from pytorch_distributed_mnist_tpu.parallel.ring import (
                ring_attention,
            )

            # The ring's blockwise online softmax IS the attention; a
            # popped flash kernel has nowhere to go. The guard above must
            # keep ring+flash unreachable — assert the coupling locally.
            assert local_attn is None, "ring+flash must be rejected earlier"
            model_kwargs["attention_fn"] = _partial(
                ring_attention, mesh=mesh, axis="seq", batch_axis="data",
                head_axis="model" if tp > 1 else None,
            )
    elif tp > 1 and pp == 1 and model_kwargs.get("attention_fn") is not None:
        # --tensor-parallel + --attention flash (sp == 1): shard_map the
        # kernel over batch x heads so it matches the Megatron layout
        # (qkv/proj weights head-sharded on 'model') with no gather.
        # (Under --pipeline-stages the kernel needs no wrapper at all:
        # the explicit-TP stage body already hands it this device's local
        # (B, T, H/tp, D) heads, parallel/pipeline_tp.py.)
        from functools import partial as _partial

        from pytorch_distributed_mnist_tpu.ops.pallas.flash import (
            sharded_flash_attention,
        )

        num_heads = _vit_num_heads()
        if num_heads % tp:
            raise SystemExit(
                f"--attention flash with --tensor-parallel {tp}: the "
                f"kernel shards the ViT's {num_heads} heads over the "
                f"model axis, so the width must divide {num_heads}"
            )
        dp_width = jax.device_count() // (tp * sp)
        micro = args.batch_size // grad_accum
        if micro % dp_width:
            # shard_map requires exact divisibility (GSPMD pads; manual
            # regions cannot) — fail with flag-level language, not a
            # jit-time sharding trace error.
            raise SystemExit(
                f"--attention flash with --tensor-parallel {tp}: the "
                f"per-step batch ({micro}) must divide evenly over the "
                f"{dp_width} data slices for the kernel's shard_map"
            )
        del model_kwargs["attention_fn"]
        init_model = get_model(args.model, **model_kwargs)
        model_kwargs["attention_fn"] = _partial(
            sharded_flash_attention, mesh=mesh, batch_axis="data",
            head_axis="model",
        )
    if moe_dispatch != "dense":
        if not model_accepts(args.model, "dispatch"):
            raise SystemExit(
                f"--moe-dispatch only applies to MoE models; "
                f"{args.model!r} does not accept a dispatch mode"
            )
        if ep > 1:
            # Params are dispatch-independent; init must use the dense
            # twin (the batch-1 init trace can't divide the dispatch
            # shard_map's token groups), then the capacity apply_fn is
            # swapped in — the same pattern as the SP/flash branches.
            # The mesh rides into the model for the all_to_all across
            # the expert axis; at ep == 1 buffers stay local, no mesh.
            init_model = get_model(args.model, **model_kwargs)
            model_kwargs.update(mesh=mesh, expert_axis="expert",
                                data_axis="data")
        model_kwargs["dispatch"] = moe_dispatch
    model = get_model(args.model, **model_kwargs)
    pp_sharding = None
    # With ZeRO composing on top of the pipeline layout, the state must be
    # placed exactly ONCE, onto the composed sharding: placing here first
    # would commit the arrays stage-sharded, and re-placing them onto
    # stage x data across hosts is a cross-host reshard place_state cannot
    # do. place=False defers; shard_state_zero below does the one place.
    pp_place = getattr(args, "optimizer_sharding", "none") == "none"
    if pp > 1 and tp > 1:
        from pytorch_distributed_mnist_tpu.parallel.pipeline_tp import (
            create_pipelined_tp_vit_state,
        )

        state, pp_sharding = create_pipelined_tp_vit_state(
            model, jax.random.key(seed), mesh, data_axis="data",
            lr=args.lr, optimizer=args.optimizer, momentum=args.momentum,
            weight_decay=args.weight_decay, place=pp_place,
        )
    elif pp > 1:
        from pytorch_distributed_mnist_tpu.parallel.pipeline_vit import (
            create_pipelined_vit_state,
        )

        state, pp_sharding = create_pipelined_vit_state(
            model, jax.random.key(seed), mesh, data_axis="data",
            lr=args.lr, optimizer=args.optimizer, momentum=args.momentum,
            weight_decay=args.weight_decay, place=pp_place,
        )
    elif tp > 1 and tp_overlap:
        # Overlapped TP: explicit head-major state + the collective-matmul
        # apply_fn (parallel/tensor.py). ZeRO was rejected above, so this
        # is always the single placement.
        from pytorch_distributed_mnist_tpu.parallel.tensor import (
            create_overlap_tp_vit_state,
        )

        state, pp_sharding = create_overlap_tp_vit_state(
            model, jax.random.key(seed), mesh, data_axis="data",
            lr=args.lr, optimizer=args.optimizer, momentum=args.momentum,
            weight_decay=args.weight_decay,
        )
    else:
        state = create_train_state(
            init_model or model, jax.random.key(seed), lr=args.lr,
            optimizer=args.optimizer, momentum=args.momentum,
            weight_decay=args.weight_decay,
        )
        if init_model is not None:
            state = state.replace(apply_fn=model.apply)
    # Resume: resolution, outcome agreement, and corrupt-checkpoint
    # quarantine all live in _resume_supervised (the agreed-exit wiring).
    state, start_epoch, best_acc, resume_path = _resume_supervised(
        args, state)
    resumed = resume_path and start_epoch > 0
    if not resumed:
        # Reference precedence (:204): a resumed checkpoint's epoch wins over
        # the --start-epoch flag; the flag only applies to fresh runs.
        start_epoch = args.start_epoch

    state_sharding = pp_sharding
    tp_rules = None
    zero = getattr(args, "optimizer_sharding", "none")
    if tp > 1 and pp == 1 and not tp_overlap:
        # PP x TP and overlapped TP already placed the state (head-major
        # explicit layout, parallel/pipeline_tp.py / parallel/tensor.py);
        # the GSPMD rule table below only applies to the standard flax
        # tree.
        from pytorch_distributed_mnist_tpu.parallel.tensor import (
            shard_state,
            vit_tp_rules,
        )

        tp_rules = vit_tp_rules("model")
        if zero == "none":
            # With zero sharding, shard_state_zero below applies the TP
            # rules itself — placing here too would move the state twice.
            state, state_sharding = shard_state(state, mesh, tp_rules)
    elif ep > 1:
        # Same rule-table machinery as TP, different table: expert
        # weights shard their leading num_experts dim over 'expert'
        # (parallel/expert.py); router/embed/head replicate. ZeRO
        # composes identically (rules-first, moments claim the rest).
        from pytorch_distributed_mnist_tpu.parallel.expert import moe_ep_rules
        from pytorch_distributed_mnist_tpu.parallel.tensor import shard_state

        tp_rules = moe_ep_rules("expert")
        if zero == "none":
            state, state_sharding = shard_state(state, mesh, tp_rules)
    if zero != "none":
        if zero == "zero1" and args.optimizer not in ("adam", "adam_pallas"):
            # ZeRO-1 shards Adam's mu/nu moment trees; SGD has no moment
            # leaves, so the request would silently do nothing. (zero3
            # shards params too, which every optimizer has.)
            raise SystemExit(
                f"--optimizer-sharding zero1 requires an Adam optimizer "
                f"(got --optimizer {args.optimizer}: no mu/nu moment state "
                f"to shard)"
            )
        from pytorch_distributed_mnist_tpu.parallel.zero import shard_state_zero

        # With --tensor-parallel, the TP rule table composes: TP-ruled
        # leaves keep their layout, ZeRO claims the rest. With
        # --pipeline-stages, the pipeline's sharding tree is the base:
        # stage-sharded block moments gain a data axis on an unsharded
        # dim (stage x data), embed/head moments shard over data alone —
        # and the pipeline state arrives UNPLACED (place=False above), so
        # this is the single placement, multi-host safe (every host holds
        # the full fresh-init or checkpoint-restored value).
        state, state_sharding = shard_state_zero(
            state, mesh, rules=tp_rules,
            level=3 if zero == "zero3" else 1,
            base_sharding=pp_sharding if pp > 1 else None,
        )

    # epoch_gather / aux_weight were validated (and bound) up in the
    # flag-check block, before mesh/model/state construction.
    train_loader, test_loader, dataset_synthesized = _build_loaders(
        args, seed, mesh)
    trainer = Trainer(state, train_loader, test_loader, mesh=mesh,
                      mode=args.trainer_mode, state_sharding=state_sharding,
                      grad_accum=grad_accum, epoch_gather=epoch_gather,
                      aux_weight=aux_weight,
                      feed_window=getattr(args, "feed_window", 2),
                      staging_log=staging_log,
                      zero_overlap=zero_overlap,
                      zero_level=3 if zero == "zero3" else 1,
                      zero_bucket_mb=zero_bucket_mb,
                      zero_bucket_mb_dcn=zero_bucket_mb_dcn)
    lr_of = step_decay_schedule(args.lr)

    # Per-run compile/staging accounting (surfaced in the summary/logs
    # below); reset here so a re-entrant run() reports its own run only.
    compile_log.reset()
    staging_log.reset()
    if not args.evaluate and not getattr(args, "no_precompile", False):
        # AOT-compile every program this run will execute on background
        # threads, overlapping the first epoch's host staging below —
        # compile leaves the cold-start critical path (the whole r5
        # north-star gap) instead of serializing at first batch. With a
        # warm persistent cache the same call degenerates to fast
        # executable fetches. (--evaluate runs one program once: there
        # is nothing to overlap.)
        trainer.precompile()

    if args.evaluate:
        # Short-circuit parity (:225-228).
        supervision.set_phase("eval")
        test_loss, test_acc = trainer.evaluate()
        log0(f"Test Loss: {test_loss}, Test Acc: {test_acc}")
        return {"test_loss": test_loss.average, "test_acc": test_acc.accuracy,
                "best_acc": best_acc, "start_epoch": start_epoch,
                "epochs_run": 0,
                "failure_events": failure_events.snapshot()}

    timer = StepTimer()
    history = []
    saver = None
    if getattr(args, "async_checkpoint", False):
        from pytorch_distributed_mnist_tpu.train.checkpoint import (
            AsyncCheckpointer,
        )

        saver = AsyncCheckpointer()
    from contextlib import closing, nullcontext

    # The saver as context manager: a clean exit waits for the last write
    # (and surfaces any stashed write error); an exception still joins the
    # in-flight thread so an already-snapshotted checkpoint lands on disk
    # instead of dying with the daemon thread at interpreter exit.
    # closing(trainer) joins the in-flight epoch prefetch on EVERY exit
    # path — early break, eval/checkpoint exception, KeyboardInterrupt —
    # not just the clean one: that stage now carries a full-epoch
    # device_put, and a daemon thread mid-device_put racing interpreter
    # teardown is a crash. Listed last so it exits FIRST (before the
    # saver drains its write).
    grow_joiners = None
    with profile_trace(args.profile_dir), (
        saver if saver is not None else nullcontext()
    ), closing(trainer):
        for epoch in range(start_epoch, args.epochs):
            train_loader.set_sample_epoch(epoch)  # per-epoch reshuffle (:231)
            # No epoch follows the last one: don't stage a gather nothing
            # will consume.
            trainer.prefetch_enabled = epoch + 1 < args.epochs
            trainer.state = trainer.state.with_learning_rate(lr_of(epoch))  # (:232)
            # Only the train pass is timed; trainer.train() folds metrics to
            # host values before returning, so the measured span covers all
            # device work for the epoch and nothing else (not eval, not the
            # checkpoint write).
            supervision.set_phase(f"train@{epoch}")
            with timer.measure(len(train_loader) * args.batch_size), \
                    phase("train", epoch=epoch):
                train_loss, train_acc = trainer.train()
            supervision.set_phase(f"eval@{epoch}")
            with phase("eval", epoch=epoch):
                test_loss, test_acc = trainer.evaluate()
            # Synthetic data is stamped on EVERY epoch line (not just the
            # startup warning): a fake-data accuracy must never read as a
            # real one in a scrolled log. Real-data lines stay
            # byte-compatible with the reference's format (:216-224).
            synth_tag = ", dataset: synthetic" if dataset_synthesized else ""
            log0(f"Epoch: {epoch}/{args.epochs}, lr: {lr_of(epoch):g},"
                 f" train loss: {train_loss}, train acc: {train_acc},"
                 f" test loss: {test_loss}, test acc: {test_acc}"
                 f"{synth_tag}")
            is_best = test_acc.accuracy > best_acc  # (:245-246)
            best_acc = max(test_acc.accuracy, best_acc)
            supervision.set_phase(f"checkpoint@{epoch}")
            ckpt_kwargs = dict(
                epoch=epoch, best_acc=best_acc, is_best=is_best,
                directory=args.checkpoint_dir,
                keep_last=getattr(args, "keep_last", 0),
                # Provenance stamp for the serve-side layout gate
                # (serve/programs.py::check_checkpoint_layout): a
                # tensor/expert-trained checkpoint must be served with
                # the matching --serve-mode, not silently replicated.
                parallel_layout={"tensor": tp, "sequence": sp,
                                 "expert": ep, "pipeline": pp},
                publish=getattr(args, "publish", None) or "full",
                chunk_mb=getattr(args, "chunk_mb", 4.0),
            )
            if saver is not None:
                # The annotated span is the drain of the PREVIOUS epoch's
                # in-flight write + this epoch's host snapshot; the write
                # itself runs on the saver's thread, annotated there.
                with phase("checkpoint_drain", epoch=epoch):
                    saver.save(trainer.state, **ckpt_kwargs)
            else:
                with phase("checkpoint", epoch=epoch):
                    save_checkpoint(trainer.state, **ckpt_kwargs)
            history.append({"epoch": epoch, "train_loss": train_loss.average,
                            "train_acc": train_acc.accuracy,
                            "test_loss": test_loss.average,
                            "test_acc": test_acc.accuracy})
            if metrics_sink is not None:
                metrics_sink.write({
                    **history[-1], "lr": lr_of(epoch),
                    "best_acc": best_acc,
                    # THIS epoch's train rate, not the cumulative
                    # average (epoch 0's compile would drag it down).
                    "images_per_sec": timer.last_images_per_sec,
                    "dataset": ("synthetic" if dataset_synthesized
                                else args.dataset),
                })
            if epoch_callback is not None and epoch_callback(epoch, history[-1]):
                break
            if epoch + 1 < args.epochs:
                # The elastic grow rendezvous (no-op outside an
                # --elastic-grow supervisor): after this epoch's
                # checkpoint save, agree whether join records are
                # pending. Gated off the LAST epoch — a finished job
                # has nothing to grow for. On a yes, BREAK rather than
                # raise: the saver context below must exit CLEANLY so
                # an async saver's deferred publish barrier runs — only
                # then does yield_for_grow exit the process, and the
                # grown world really resumes from THIS epoch.
                grow_joiners = elastic.maybe_grow_rendezvous()
                if grow_joiners:
                    break
    if grow_joiners:
        # Saver context exited cleanly above: every checkpoint —
        # including an async saver's deferred sharded publish — is on
        # disk and published. Now (and only now) the generation may
        # yield; the grown world resumes from the epoch just trained.
        elastic.yield_for_grow(grow_joiners)
    supervision.set_phase("shutdown")
    ips = timer.images_per_sec
    log0(f"throughput: {ips:,.0f} images/sec "
         f"({timer.images_per_sec_per_chip:,.0f}/chip), best acc: {best_acc * 100:.2f}%")
    staging = staging_log.summary()
    if staging["stages"]:
        # The input-plane story in one line: what feeding the chip cost
        # and how much of it the pipeline hid behind compute.
        log0(f"input plane: {staging['feed_images_per_sec']:,.0f} "
             f"feed images/sec (host {staging['host_ms']:.0f} ms + H2D "
             f"{staging['h2d_ms']:.0f} ms over {staging['stages']} "
             f"stages, {staging['pipelined_stages']} pipelined), "
             f"consumer blocked {staging['consumer_wait_ms']:.0f} ms, "
             f"overlap {staging['overlap_fraction']:.0%}")
    compile_stats = compile_log.stats()
    for prog, rec in compile_stats["programs"].items():
        hit = rec["persistent_cache_hit"]
        cache = ("cache off" if hit is None
                 else "cache hit" if hit else "cache miss")
        log0(f"compile[{prog}]: {rec['wall_ms']:.0f} ms "
             f"({rec['backend_compiles']} XLA compile(s), {cache})")
    events = failure_events.snapshot()
    for ev in events:
        # Retries/quarantines the run survived still belong in the log —
        # a checkpoint that needed three publish attempts is a disk
        # about to fail, visible only if someone can see the near-miss.
        log0(f"supervision[{ev['kind']}]: {ev['detail']}")
    return {"best_acc": best_acc, "history": history,
            "compile_stats": compile_stats,
            "input_pipeline": staging,
            "failure_events": events,
            "images_per_sec": ips,
            "images_per_sec_per_chip": timer.images_per_sec_per_chip,
            # Final epoch's rate: steady-state throughput once the epoch
            # program is compiled (the cumulative figure above folds epoch
            # 0's compile into the denominator — on a 2-epoch smoke run
            # that understates a v5e by ~500x).
            "images_per_sec_per_chip_last_epoch":
                timer.last_images_per_sec_per_chip,
            "dataset_synthesized": dataset_synthesized,
            "start_epoch": start_epoch,
            "epochs_run": len(history)}


def main(argv: Optional[list] = None) -> None:
    import sys as _sys

    argv = list(_sys.argv[1:]) if argv is None else list(argv)
    if argv and argv[0] == "serve":
        # The serving subsystem: `tpu-mnist serve --checkpoint-dir ...`
        # boots the bucketed AOT inference engine + micro-batcher + hot
        # reload watcher over a training run's checkpoint directory
        # (serve/server.py); `--serve-devices N` scales the data plane
        # to N engine replicas x N local chips with `--max-inflight`
        # pipelined dispatch (serve/pool.py). A subcommand, not a flag:
        # serving has its own flag surface and lifecycle (a process that
        # never exits).
        from pytorch_distributed_mnist_tpu.serve.server import (
            main as serve_main,
        )

        serve_main(argv[1:])
        return
    if argv and argv[0] == "route":
        # The fleet tier: `tpu-mnist route --backends host:port,...`
        # boots the pure-stdlib routing front-end over N backend serve
        # processes — health-gated failover, consistent-hash client
        # affinity, rolling deploys + fleet canaries via POST /rollout,
        # and the two-tier fleet autoscaler (serve/router.py). Kept a
        # subcommand for the same reason `serve` is: its own flag
        # surface and lifecycle, and it must import NONE of the jax
        # stack (a router shares no fate with its data plane).
        from pytorch_distributed_mnist_tpu.serve.router import (
            main as route_main,
        )

        route_main(argv[1:])
        return
    args = build_parser().parse_args(argv)
    if args.elastic and not args.spawn:
        raise SystemExit(
            "--elastic supervises the worker processes it spawns, so it "
            "requires --spawn N (the local world). On a real pod the "
            "restart actor is the cluster manager — "
            "runtime/elastic.py::supervise is the reference "
            "implementation to integrate there."
        )
    if args.min_world < 1:
        raise SystemExit(f"--min-world must be >= 1, got {args.min_world}")
    if args.elastic and args.min_world > args.spawn:
        raise SystemExit(
            f"--min-world {args.min_world} exceeds the initial world "
            f"size --spawn {args.spawn}"
        )
    if (args.elastic_grow or args.max_world) and not args.elastic:
        raise SystemExit(
            "--elastic-grow/--max-world shape the elastic supervisor's "
            "grow direction; they require --elastic (and --spawn N)"
        )
    if args.max_world < 0 or (args.elastic and args.max_world
                              and args.max_world < args.spawn):
        raise SystemExit(
            f"--max-world {args.max_world} is below the initial world "
            f"size --spawn {args.spawn} (0 = unbounded)"
        )
    if args.spawn:
        if args.spawn < 2:
            raise SystemExit(
                f"--spawn {args.spawn}: the local spawner simulates a "
                "multi-host world and needs at least 2 processes; for a "
                "single-process run just drop --spawn"
            )
        if (args.coordinator or args.process_id is not None
                or args.num_processes is not None):
            raise SystemExit(
                "--spawn forks its own local world; it cannot combine with "
                "--coordinator/--num-processes/--process-id (those join an "
                "existing one)"
            )
        if args.elastic:
            # The elastic supervisor: same local world as --spawn, but a
            # host loss shrinks it (survivors re-exec at W-1 resumed
            # from the last published checkpoint) instead of ending it —
            # and with --elastic-grow, announced joiners grow it back.
            raise SystemExit(elastic.supervise(
                args.spawn, argv, min_world=args.min_world,
                max_world=args.max_world, grow=args.elastic_grow))
        from pytorch_distributed_mnist_tpu.parallel.launcher import spawn_local

        raise SystemExit(spawn_local(args.spawn, argv))
    run(args)


if __name__ == "__main__":
    main()
