"""Learning-rate schedules.

Parity target: ``adjust_learning_rate`` at
``/root/reference/multi_proc_single_gpu.py:257-261`` — step decay
``lr = base_lr * 0.1 ** (epoch // 10)``, applied once per epoch.

The reference mutates optimizer param groups in-place each epoch; the TPU
design instead passes the epoch's LR into the jitted step through an optax
``inject_hyperparams`` wrapper, so the step function stays pure and the
schedule stays a trivially unit-testable function (SURVEY.md section 4).
"""

from __future__ import annotations


def step_decay_schedule(base_lr: float, decay_factor: float = 0.1, decay_every: int = 10):
    """Return ``lr(epoch)`` implementing the reference's step decay (``:259``)."""

    def lr(epoch: int) -> float:
        return base_lr * decay_factor ** (epoch // decay_every)

    return lr
