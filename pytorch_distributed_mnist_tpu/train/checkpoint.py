"""Checkpoint save / resume.

Schema parity with the reference's richest auxiliary subsystem
(``/root/reference/multi_proc_single_gpu.py:249-255, 263-271, 197-214``):

- checkpoint dict ``{epoch: epoch+1, state_dict, best_acc, optimizer}``
  becomes ``{epoch, best_acc}`` metadata + the flattened
  ``{params, opt_state, step}`` leaf arrays;
- one file per epoch (``checkpoint_{epoch}.npz``) plus a ``model_best``
  copy on improvement (``:267-271``; every epoch's file retained, no GC,
  same as the reference);
- only process 0 writes (``:248-249``);
- restore maps the saved arrays onto the *current* mesh: the analog of
  ``torch.load(map_location=device)`` (``:202``) is ``device_put`` with each
  leaf's target sharding, which is restore-time resharding — so a run
  trained on 8 chips restores for single-chip ``--evaluate``
  (BASELINE.json configs 3-4);
- writes are atomic (tmp file + ``os.replace``), which the reference is not
  — a rank killed mid-``torch.save`` leaves a truncated file there.

Format: ``.npz`` (zip of npy arrays) + a JSON sidecar inside the archive —
no pickle, no framework-versioned opaque bytes; leaves are matched to a
*template* state at restore time, the same contract as
``load_state_dict`` needing a constructed model (``:209``).
"""

from __future__ import annotations

import io
import json
import os
import shutil
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

CHECKPOINT_DIR = "checkpoints"


def _leaves_with_names(tree: Any):
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in flat]


def save_checkpoint(
    state,
    *,
    epoch: int,
    best_acc: float,
    is_best: bool,
    directory: str = CHECKPOINT_DIR,
    process_index: Optional[int] = None,
) -> Optional[str]:
    """Write ``checkpoint_{epoch}.npz`` (+ best copy); returns the path.

    ``epoch`` is stored as ``epoch + 1`` — the reference's convention
    (``:251``) so resume continues at the *next* epoch (``:204``). Only
    process 0 writes (``:248-249``); other processes return None.
    """
    pid = jax.process_index() if process_index is None else process_index
    if pid != 0:
        return None
    os.makedirs(directory, exist_ok=True)
    named = _leaves_with_names({"params": state.params, "opt_state": state.opt_state,
                               "step": state.step})
    payload: Dict[str, np.ndarray] = {f"leaf_{i}": np.asarray(v) for i, (_, v) in enumerate(named)}
    meta = {
        "epoch": epoch + 1,
        "best_acc": float(best_acc),
        "leaf_names": [k for k, _ in named],
        "format_version": 1,
    }
    buf = io.BytesIO()
    np.savez(buf, __meta__=np.frombuffer(json.dumps(meta).encode(), np.uint8), **payload)
    path = os.path.join(directory, f"checkpoint_{epoch}.npz")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(buf.getvalue())
    os.replace(tmp, path)  # atomic publish
    if is_best:
        best = os.path.join(directory, "model_best.npz")
        shutil.copyfile(path, best + ".tmp")
        os.replace(best + ".tmp", best)
    return path


def load_checkpoint(path: str, state) -> Tuple[Any, int, float]:
    """Restore ``(state, start_epoch, best_acc)`` from ``path`` onto ``state``'s shardings.

    ``state`` is the freshly-constructed template (model + optimizer built
    exactly as at save time — the ``load_state_dict`` contract, ``:209-210``).
    Each saved leaf is ``device_put`` with the template leaf's sharding:
    restore-time resharding across mesh shapes.
    """
    with np.load(path) as z:
        meta = json.loads(bytes(z["__meta__"]).decode())
        saved = [z[f"leaf_{i}"] for i in range(len(meta["leaf_names"]))]
    tmpl_tree = {"params": state.params, "opt_state": state.opt_state, "step": state.step}
    flat, treedef = jax.tree_util.tree_flatten(tmpl_tree)
    if len(flat) != len(saved):
        raise ValueError(
            f"{path}: checkpoint has {len(saved)} leaves, current state has "
            f"{len(flat)} — model/optimizer mismatch"
        )
    restored = []
    for i, (tmpl, arr) in enumerate(zip(flat, saved)):
        if tuple(np.shape(tmpl)) != arr.shape:
            raise ValueError(
                f"{path}: leaf {meta['leaf_names'][i]} shape {arr.shape} != "
                f"expected {tuple(np.shape(tmpl))}"
            )
        arr = arr.astype(np.asarray(tmpl).dtype) if hasattr(tmpl, "dtype") else arr
        sharding = getattr(tmpl, "sharding", None)
        restored.append(jax.device_put(arr, sharding) if sharding is not None else arr)
    tree = jax.tree_util.tree_unflatten(treedef, restored)
    new_state = state.replace(
        params=tree["params"], opt_state=tree["opt_state"], step=tree["step"]
    )
    return new_state, int(meta["epoch"]), float(meta["best_acc"])


def try_resume(path: str, state) -> Tuple[Any, int, float]:
    """Reference resume policy (``:197-214``): load if the file exists, else
    warn and continue fresh with ``(state, 0, 0.0)``."""
    if path and os.path.isfile(path):
        state, start_epoch, best_acc = load_checkpoint(path, state)
        print(f"=> loaded checkpoint '{path}' (epoch {start_epoch})")
        return state, start_epoch, best_acc
    if path:
        print(f"=> no checkpoint found at '{path}'")
    return state, 0, 0.0
