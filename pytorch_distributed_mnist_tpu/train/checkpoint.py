"""Checkpoint save / resume.

Schema parity with the reference's richest auxiliary subsystem
(``/root/reference/multi_proc_single_gpu.py:249-255, 263-271, 197-214``):

- checkpoint dict ``{epoch: epoch+1, state_dict, best_acc, optimizer}``
  becomes ``{epoch, best_acc}`` metadata + the flattened
  ``{params, opt_state, step}`` leaf arrays;
- one file per epoch (``checkpoint_{epoch}.npz``) plus a ``model_best``
  copy on improvement (``:267-271``; every epoch's file retained, no GC,
  same as the reference);
- only process 0 writes (``:248-249``);
- restore maps the saved arrays onto the *current* mesh: the analog of
  ``torch.load(map_location=device)`` (``:202``) is ``device_put`` with each
  leaf's target sharding, which is restore-time resharding — so a run
  trained on 8 chips restores for single-chip ``--evaluate``
  (BASELINE.json configs 3-4);
- writes are atomic (tmp file + ``os.replace``), which the reference is not
  — a rank killed mid-``torch.save`` leaves a truncated file there.

Format: ``.npz`` (zip of npy arrays) + a JSON sidecar inside the archive —
no pickle, no framework-versioned opaque bytes; leaves are matched to a
*template* state at restore time, the same contract as
``load_state_dict`` needing a constructed model (``:209``).

Two layouts, chosen automatically at save time:

- **npz file** (``checkpoint_{e}.npz``) when every leaf is fully
  addressable from this process — single-host runs, and multi-host DP
  where params/moments are replicated. One process-0 write, as the
  reference does (``:248-249``).
- **sharded directory** (``checkpoint_{e}.ckpt/``) when any leaf spans
  non-addressable devices (multi-host TP/EP/ZeRO states, where
  ``np.asarray(leaf)`` would raise): every process writes only the shards
  it owns (``shard.replica_id == 0`` de-dupes replicas) into its own
  ``shards_p{pid}.npz`` + slice-index JSON, process 0 writes the global
  ``meta.json``, and the directory is atomically published after a
  cross-host barrier. Restore stitches the global array from the slice
  index and redistributes onto the template's shardings — so the layout
  round-trips across different mesh shapes, same as the npz path.

Cross-world resharding contract: BOTH layouts restore onto any world —
any process count, any mesh, any optimizer-sharding level the template
was built with — because restore always goes through full host arrays
and the template's own shardings (``_restore_onto_template``; for ZeRO
states the specs are ``parallel/zero.py::zero_state_sharding``'s, so a
resumed state is bit-identical to a fresh shard of the gathered
arrays). This is what lets the elastic runtime (``runtime/elastic.py``)
resume a checkpoint saved at world size W on the W' survivors of a host
loss, and a serve pool reload across topologies. The saving world is
stamped in meta (``checkpoint_world``) as inspectable provenance;
``tests/test_reshard.py`` pins the (W, W') round-trip matrix.
"""

from __future__ import annotations

import io
import json
import os
import re
import shutil
import sys
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from pytorch_distributed_mnist_tpu.runtime.supervision import maybe_fault
from pytorch_distributed_mnist_tpu.utils.watchdog import retry_with_backoff

CHECKPOINT_DIR = "checkpoints"

# Quarantine suffix for corrupt checkpoints (resume-time rename); the
# `_epoch_checkpoints` pattern can never match a quarantined name, so a
# quarantined file is invisible to resolution and pruning alike.
CORRUPT_SUFFIX = ".corrupt"


def _leaves_with_names(tree: Any):
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in flat]


def _state_tree(state) -> Dict[str, Any]:
    return {"params": state.params, "opt_state": state.opt_state,
            "step": state.step}


def _world_stamp() -> Dict[str, int]:
    """The saving world's shape, stamped into checkpoint meta (both
    layouts) as provenance: the elastic resume path and serve boot can
    see — by meta inspection, before any array bytes move — that a
    checkpoint was saved at a different world size and will be
    re-sharded onto this one. The restore path never *requires* a
    match: ``_restore_onto_template`` re-shards any layout onto any
    process count and mesh (the cross-world contract
    ``tests/test_reshard.py`` pins)."""
    return {"processes": int(jax.process_count()),
            "devices": int(jax.device_count())}


def _npz_saveable(leaf: Any) -> bool:
    """True when ``np.asarray(leaf)`` works on this process: the leaf is
    fully addressable (single host) or fully replicated (multi-host DP —
    every host holds the whole value). Only genuinely cross-host-sharded
    leaves (multi-host TP/EP/ZeRO) need the sharded directory layout."""
    return bool(getattr(leaf, "is_fully_addressable", True)
                or getattr(leaf, "is_fully_replicated", False))


def save_checkpoint(
    state,
    *,
    epoch: int,
    best_acc: float,
    is_best: bool,
    directory: str = CHECKPOINT_DIR,
    process_index: Optional[int] = None,
    layout: Optional[str] = None,
    keep_last: int = 0,
    parallel_layout: Optional[Dict[str, Any]] = None,
    publish: Optional[str] = None,
    chunk_mb: float = 4.0,
) -> Optional[str]:
    """Write ``checkpoint_{epoch}.npz`` (+ best copy); returns the path.

    ``epoch`` is stored as ``epoch + 1`` — the reference's convention
    (``:251``) so resume continues at the *next* epoch (``:204``). Only
    process 0 writes (``:248-249``); other processes return None — except
    when a leaf spans non-addressable devices (multi-host sharded state),
    where every process contributes its own shards to a ``.ckpt``
    directory instead.

    ``parallel_layout`` stamps the run's training parallelism into the
    checkpoint meta (``{"tensor": w, "expert": w, "sequence": w,
    "pipeline": w}`` widths; the CLI passes its flag values) — the
    provenance the serve boot/reload layout gate
    (``serve/programs.py::check_checkpoint_layout``) reads so an
    expert/tensor-trained checkpoint cannot be silently served under a
    mismatched ``--serve-mode``. ``None`` (library callers, old files)
    writes no field and the gate passes everything.
    """
    if layout not in (None, "npz", "sharded"):
        raise ValueError(f"unknown checkpoint layout {layout!r}")
    if publish not in (None, "full", "delta"):
        raise ValueError(f"unknown publish mode {publish!r}")
    if publish == "delta":
        # Content-addressed delta publish (``--publish delta``): chunks
        # absent from the store + an atomic manifest INSTEAD of the npz
        # file. Resume, watcher resolution, and pruning all already
        # treat the manifest as a first-class checkpoint via the shared
        # ``_epoch_checkpoints`` pattern. An explicit sharded-layout
        # request is contradictory (the manifest replaces the npz
        # layout) and cross-host sharded states are rejected loudly
        # inside ``publish_state`` — both route the caller to: save the
        # sharded layout, then convert with ``publish_from_checkpoint``.
        if layout == "sharded":
            raise ValueError(
                "--publish delta replaces the npz layout and cannot "
                "write layout='sharded'; save the sharded layout and "
                "convert via publish_from_checkpoint")
        from pytorch_distributed_mnist_tpu.distrib.publish import (
            publish_state,
        )

        return publish_state(
            state, epoch=epoch, best_acc=best_acc, directory=directory,
            chunk_mb=chunk_mb, is_best=is_best, keep_last=keep_last,
            process_index=process_index, parallel_layout=parallel_layout)
    pid = jax.process_index() if process_index is None else process_index
    named = _leaves_with_names(_state_tree(state))
    if layout == "sharded" or (
        layout is None and not all(_npz_saveable(v) for _, v in named)
    ):
        return _save_sharded(
            named, epoch=epoch, best_acc=best_acc, is_best=is_best,
            directory=directory, pid=pid, keep_last=keep_last,
            parallel_layout=parallel_layout,
        )
    if pid != 0:
        return None
    os.makedirs(directory, exist_ok=True)
    payload: Dict[str, np.ndarray] = {f"leaf_{i}": np.asarray(v) for i, (_, v) in enumerate(named)}
    meta = {
        "epoch": epoch + 1,
        "best_acc": float(best_acc),
        "leaf_names": [k for k, _ in named],
        "format_version": 1,
        "world": _world_stamp(),
    }
    if parallel_layout is not None:
        meta["parallel_layout"] = dict(parallel_layout)
    buf = io.BytesIO()
    np.savez(buf, __meta__=np.frombuffer(json.dumps(meta).encode(), np.uint8), **payload)
    path = os.path.join(directory, f"checkpoint_{epoch}.npz")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(buf.getvalue())
    os.replace(tmp, path)  # atomic publish
    if is_best:
        best = os.path.join(directory, "model_best.npz")
        shutil.copyfile(path, best + ".tmp")
        os.replace(best + ".tmp", best)
    prune_checkpoints(directory, keep_last)
    return path


def _shard_slices(leaf, shard) -> Tuple[list, list]:
    """Normalize a shard's index into explicit [start], [stop] lists."""
    starts, stops = [], []
    for sl, dim in zip(shard.index, leaf.shape):
        a, b, _ = sl.indices(dim)
        starts.append(int(a))
        stops.append(int(b))
    return starts, stops


def _sharded_prepare(directory: str, epoch: int, pid: int) -> Tuple[str, str]:
    """Phase 1 (main thread, collective): clean + create the tmp dir.

    Returns ``(tmp, final)``. Contains a cross-host collective, so it
    must run on the thread that owns the device (never a writer thread).
    Process 0's local filesystem work is wrapped in the phase agreement:
    a cleanup failure fails every host together rather than process 0
    raising alone while its peers block in the synchronization — the
    agreement collective doubles as the nobody-writes-into-a-dir-
    being-rm'd barrier. Creating each host's own view of ``tmp`` is left
    to the callers' guarded produce phase for the same reason."""
    maybe_fault("ckpt_prepare")
    final = os.path.join(directory, f"checkpoint_{epoch}.ckpt")
    tmp = final + ".tmp"  # same deterministic name on every process
    err: Optional[BaseException] = None
    if pid == 0:
        try:
            # A crashed earlier attempt may have left stale shard files
            # here; publishing those alongside fresh ones would silently
            # corrupt the restore (stale index records overwrite
            # freshly-stitched regions).
            if os.path.isdir(tmp):
                shutil.rmtree(tmp)
            os.makedirs(tmp)
        except Exception as exc:
            err = exc
    _agree_phase_ok(err, epoch, "prepare",
                    f"tmp dir {tmp} could not be prepared")
    return tmp, final


def _sharded_collect(named, pid: int) -> Tuple[Dict[str, np.ndarray], list]:
    """Phase 2 (main thread, device reads): host copies of OWNED shards.

    Ownership = ``shard.replica_id == 0``: exactly one device globally
    holds replica 0 of each distinct shard, so replicated leaves (and the
    replicated dims of partially-sharded ones) are written once, not once
    per host. ``np.asarray(shard.data)`` is a D2H copy, so the returned
    payload is a consistent snapshot — the train loop may donate the
    device buffers the moment this returns."""
    maybe_fault("ckpt_collect")
    payload: Dict[str, np.ndarray] = {}
    index = []
    for i, (_, leaf) in enumerate(named):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:  # plain host array (e.g. python scalar leaf)
            if pid == 0:
                key = f"leaf{i}_s0"
                arr = np.asarray(leaf)
                payload[key] = arr
                index.append({"leaf": i, "key": key,
                              "start": [0] * arr.ndim,
                              "stop": list(arr.shape)})
            continue
        for j, shard in enumerate(shards):
            if shard.replica_id != 0:
                continue
            key = f"leaf{i}_s{j}"
            payload[key] = np.asarray(shard.data)
            starts, stops = _shard_slices(leaf, shard)
            index.append({"leaf": i, "key": key, "start": starts,
                          "stop": stops})
    return payload, index


def _sharded_meta(named, epoch: int, best_acc: float,
                  parallel_layout: Optional[Dict[str, Any]] = None
                  ) -> Dict[str, Any]:
    meta = {
        "epoch": epoch + 1,
        "best_acc": float(best_acc),
        "leaf_names": [k for k, _ in named],
        "global_shapes": [list(np.shape(v)) for _, v in named],
        "dtypes": [np.dtype(getattr(v, "dtype", np.float32)).name
                   for _, v in named],
        "format_version": 2,
        "world": _world_stamp(),
    }
    if parallel_layout is not None:
        meta["parallel_layout"] = dict(parallel_layout)
    return meta


def _sharded_write_files(tmp: str, pid: int, payload, index,
                         meta: Optional[Dict[str, Any]]) -> None:
    """Phase 3 (any thread): pure file I/O, no device or collective use —
    the part the AsyncCheckpointer overlaps with the next epoch."""
    maybe_fault("ckpt_write")
    shard_file = f"shards_p{pid:05d}.npz"
    if payload:
        with open(os.path.join(tmp, shard_file), "wb") as f:
            np.savez(f, **payload)
    with open(os.path.join(tmp, f"index_p{pid:05d}.json"), "w") as f:
        json.dump({"file": shard_file if payload else None,
                   "shards": index}, f)
    if meta is not None:  # pid 0 only
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f)


def _publish_dir(tmp: str, final: str, directory: str, epoch: int,
                 is_best: bool, keep_last: int) -> None:
    """Process 0's publish body: shared-fs check, atomic rename, best
    copy, GC. Factored out so the multi-process fault tests can inject a
    failure here and pin that it fails EVERY host (see _sharded_publish).
    """
    # Shared-filesystem check: every host's index file must be visible
    # here, or the published checkpoint would be missing their shards
    # (and resume would diverge: host 0 errors, others start fresh).
    missing = [
        p for p in range(jax.process_count())
        if not os.path.isfile(os.path.join(tmp, f"index_p{p:05d}.json"))
    ]
    if missing:
        raise RuntimeError(
            f"sharded checkpoint save: index files from processes "
            f"{missing} are not visible in {tmp} — --checkpoint-dir "
            f"must be a filesystem shared by all hosts"
        )
    if os.path.isdir(final):
        shutil.rmtree(final)

    # Atomic publish of the complete directory. The rename is the one
    # retry-safe step on a network filesystem (transient ESTALE/EIO on a
    # busy NFS export): bounded backoff+jitter, because failing here
    # aborts EVERY host via the publish agreement while a one-line retry
    # publishes a checkpoint that is already fully on disk.
    from pytorch_distributed_mnist_tpu.utils.profiling import failure_events

    def _replace_once() -> None:
        try:
            os.replace(tmp, final)
        except OSError:
            if os.path.isdir(final) and not os.path.exists(tmp):
                # NFS lost-reply duplicate: the server performed the
                # rename but the client's reply was lost, so the retry
                # sees ENOENT for tmp. The publish already landed —
                # treating this as failure would abort EVERY host over a
                # checkpoint that is intact on disk.
                return
            raise

    retry_with_backoff(
        _replace_once,
        attempts=3, retry_on=(OSError,),
        on_retry=lambda attempt, exc, delay: failure_events.record(
            "publish_retry",
            f"rename to {final} attempt {attempt} failed ({exc!r}); "
            f"retrying in {delay:.2f}s"),
    )
    try:
        if is_best:
            best = os.path.join(directory, "model_best.ckpt")
            best_tmp = best + ".copy_tmp"
            if os.path.isdir(best_tmp):
                shutil.rmtree(best_tmp)
            shutil.copytree(final, best_tmp)
            if os.path.isdir(best):
                shutil.rmtree(best)
            os.replace(best_tmp, best)
        prune_checkpoints(directory, keep_last)
    except Exception as exc:
        # The rename above already landed: say so, or the phase-failure
        # message would misdirect a postmortem into discarding (or
        # re-running) a checkpoint that IS valid on disk.
        raise RuntimeError(
            f"checkpoint {final} WAS published, but a post-publish step "
            f"(best copy / prune) failed: {exc!r}"
        ) from exc


def _sharded_publish(tmp: str, final: str, directory: str, epoch: int,
                     is_best: bool, keep_last: int, pid: int) -> str:
    """Phase 4 (main thread, collective): barrier until every host's
    files are on disk, then process 0 atomically publishes the dir.

    ``directory`` must be a filesystem shared by all hosts (the same
    assumption the reference makes for every rank loading rank 0's file,
    ``:202``); process 0 verifies that after the write barrier by checking
    every host's index file is visible before publishing. Process 0's
    publish outcome is AGREED before anyone proceeds: that RuntimeError
    (a real misconfiguration a user can hit) previously raised on
    process 0 alone while every peer blocked in the trailing barrier
    forever. The agreement collective doubles as the
    no-reader-races-a-half-published-dir barrier.

    ORDERING CONTRACT: callers must run the write-phase
    ``_agree_phase_ok`` immediately before this function (both call
    sites do) — that agreement is the all-shard-files-are-on-disk
    barrier, so no extra collective runs here before process 0 checks
    visibility."""
    maybe_fault("ckpt_publish")
    err: Optional[BaseException] = None
    if pid == 0:
        try:
            _publish_dir(tmp, final, directory, epoch, is_best, keep_last)
        except Exception as exc:
            err = exc
    _agree_phase_ok(err, epoch, "publish",
                    f"checkpoint dir {final} may not have been published "
                    f"— see the failed host's log (a post-publish "
                    f"best-copy/prune failure leaves it valid on disk)")
    return final


def _agree_phase_ok(error: Optional[BaseException], epoch: int,
                    phase: str, detail: str) -> None:
    """Agree a per-host phase outcome before anyone proceeds past it.

    The sharded layout's barriers have no timeout, so a host raising its
    local error while its peers enter the next collective would hang the
    job forever (round-4/5 advisor — this held for shard writes, tmp-dir
    prepare, and process 0's publish body alike). Every host calls this
    at the same logical step; afterwards all hosts either proceed
    together or raise together — peers of a failed host raise
    ``PeerFailure`` naming it, the failed host re-raises its own error.

    Since the supervision retrofit this delegates to
    ``runtime/supervision.py``: the agreement exchanges full supervision
    records (so a poison pill from a host that failed OUTSIDE a
    checkpoint phase is understood here and attributed to its real
    phase), runs under the configured watchdog deadline, and the
    allgather itself synchronizes, so callers may rely on this as a
    barrier.
    """
    from pytorch_distributed_mnist_tpu.runtime import supervision

    if jax.process_count() > 1:
        failed = supervision.agree(f"ckpt_{phase}", error)
        if failed and error is None:
            raise supervision.PeerFailure(
                supervision.peer_failure_message(
                    failed,
                    f"sharded checkpoint {phase} for epoch {epoch} failed "
                    f"on host(s) {[h for h, _, _ in failed]}; {detail}",
                ),
                hosts=[h for h, _, _ in failed],
                # The failed peer's OWN reported phase: a poison pill
                # from a host that died outside checkpointing must be
                # attributed to its real failure site, not to whichever
                # checkpoint agreement happened to receive the pill.
                phase=failed[0][1],
                reason=failed[0][2],
            )
    if error is not None:
        raise error


def _save_sharded(named, *, epoch: int, best_acc: float, is_best: bool,
                  directory: str, pid: int, keep_last: int = 0,
                  parallel_layout: Optional[Dict[str, Any]] = None) -> str:
    """Every process writes its owned shards; process 0 publishes the dir.

    Synchronous composition of the four phases; the AsyncCheckpointer
    runs phases 1-2 inline, phase 3 on its writer thread, and phase 4 at
    the next main-thread drain point."""
    tmp, final = _sharded_prepare(directory, epoch, pid)
    err: Optional[BaseException] = None
    try:
        # The WHOLE produce-this-host's-files phase is under the
        # agreement — a collect (device read) or meta failure outside it
        # would strand peers in the agreement collective just as a write
        # failure once stranded them in the publish barrier. Exception,
        # not BaseException: a KeyboardInterrupt on the main thread must
        # propagate immediately, not be held hostage by an allgather.
        os.makedirs(tmp, exist_ok=True)  # this host's view of the dir
        payload, index = _sharded_collect(named, pid)
        meta = (_sharded_meta(named, epoch, best_acc, parallel_layout)
                if pid == 0 else None)
        _sharded_write_files(tmp, pid, payload, index, meta)
    except Exception as exc:
        err = exc
    _agree_phase_ok(err, epoch, "write", f"dropping unpublished {tmp}")
    return _sharded_publish(tmp, final, directory, epoch, is_best,
                            keep_last, pid)


def _load_sharded(path: str, state) -> Tuple[Any, int, float]:
    """Stitch global arrays from the shard index, redistribute to ``state``.

    World-agnostic by construction, and that generality is load-bearing
    (the elastic runtime's reshard-resume path, ``runtime/elastic.py``):
    the shard index is keyed by global slice regions, not by the saving
    world's topology, so the loader reads WHATEVER set of per-process
    index files the directory holds, assembles each full global array
    on the host, and hands it to ``_restore_onto_template`` to place
    with the template leaf's sharding. A state saved from a ``(4, 2)``
    mesh of 4 processes restores onto an ``(8,)`` mesh, a single
    device, or a 3-process shrunk world unchanged — the loading world's
    process count and mesh never have to match the saving world's.

    The saving world's shape (``meta["world"]``, when stamped) is used
    only for diagnostics: a shard-coverage gap is reported as the
    incomplete filesystem view it is, naming how many index files the
    saving world wrote versus how many are visible here.
    """
    meta, globals_np = _stitch_sharded(path)
    new_state = _restore_onto_template(
        path, meta["leaf_names"], globals_np, state
    )
    return new_state, int(meta["epoch"]), float(meta["best_acc"])


def _stitch_sharded(path: str) -> Tuple[Dict[str, Any], list]:
    """The sharded layout's host-side stitch: ``(meta, global arrays)``
    assembled from the per-process shard index — shared by the restore
    path and by ``read_checkpoint_arrays`` (the delta publish converter
    reads a ``.ckpt`` dir through this, so a multi-host sharded save
    can be republished as a manifest without a template state)."""
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    n_leaves = len(meta["leaf_names"])
    globals_np = [
        np.zeros(shape, dtype=np.dtype(dt))
        for shape, dt in zip(meta["global_shapes"], meta["dtypes"])
    ]
    filled = [0] * n_leaves
    index_files = 0
    for idx_name in sorted(os.listdir(path)):
        if not idx_name.startswith("index_p"):
            continue
        index_files += 1
        with open(os.path.join(path, idx_name)) as f:
            idx = json.load(f)
        if idx["file"] is None:
            continue
        shard_path = os.path.join(path, idx["file"])
        if not os.path.isfile(shard_path):
            continue  # the filled-element check below reports what's missing
        with np.load(shard_path) as z:
            for rec in idx["shards"]:
                i = rec["leaf"]
                region = tuple(
                    slice(a, b) for a, b in zip(rec["start"], rec["stop"])
                )
                data = z[rec["key"]]
                globals_np[i][region] = data.reshape(globals_np[i][region].shape)
                filled[i] += data.size
    saved_procs = (meta.get("world") or {}).get("processes")
    for i, (total, arr) in enumerate(zip(filled, globals_np)):
        if total < arr.size:
            world = (f" (saved by a {saved_procs}-process world; "
                     f"{index_files} index file(s) visible here — an "
                     f"incomplete shared-filesystem view?)"
                     if saved_procs and index_files != saved_procs else
                     " — incomplete save?")
            raise ValueError(
                f"{path}: leaf {meta['leaf_names'][i]} is missing shards "
                f"({total}/{arr.size} elements present){world}"
            )
    return meta, globals_np


def _restore_onto_template(path, leaf_names, arrays, state):
    """Map saved host arrays onto the template state's leaves/shardings.

    Shared by both layouts: shape/count validation, dtype restore, and
    placement — ``device_put`` locally, ``make_array_from_callback`` when
    the template leaf spans non-addressable devices (each host supplies
    its own shards from the full host copy; no cross-host transfers).
    """
    flat, treedef = jax.tree_util.tree_flatten(_state_tree(state))
    if len(flat) != len(arrays):
        raise ValueError(
            f"{path}: checkpoint has {len(arrays)} leaves, current state "
            f"has {len(flat)} — model/optimizer mismatch"
        )
    restored = []
    for i, (tmpl, arr) in enumerate(zip(flat, arrays)):
        if tuple(np.shape(tmpl)) != arr.shape:
            raise ValueError(
                f"{path}: leaf {leaf_names[i]} shape {arr.shape} != "
                f"expected {tuple(np.shape(tmpl))}"
            )
        if hasattr(tmpl, "dtype"):
            arr = arr.astype(tmpl.dtype)
        sharding = getattr(tmpl, "sharding", None)
        if sharding is not None and not getattr(
            tmpl, "is_fully_addressable", True
        ):
            restored.append(jax.make_array_from_callback(
                arr.shape, sharding, lambda region, a=arr: a[region]
            ))
        elif sharding is not None:
            restored.append(jax.device_put(arr, sharding))
        else:
            restored.append(arr)
    tree = jax.tree_util.tree_unflatten(treedef, restored)
    return state.replace(
        params=tree["params"], opt_state=tree["opt_state"], step=tree["step"]
    )


def load_checkpoint(path: str, state) -> Tuple[Any, int, float]:
    """Restore ``(state, start_epoch, best_acc)`` from ``path`` onto ``state``'s shardings.

    ``state`` is the freshly-constructed template (model + optimizer built
    exactly as at save time — the ``load_state_dict`` contract, ``:209-210``).
    Each saved leaf is ``device_put`` with the template leaf's sharding:
    restore-time resharding across mesh shapes. Directory paths are the
    sharded layout; ``.manifest`` files are the content-addressed delta
    layout (assembled from the adjacent chunk store — so resume and
    serve boot read a delta-published run with no extra code path);
    other files are the npz layout.
    """
    if os.path.isdir(path):
        return _load_sharded(path, state)
    if path.endswith(".manifest"):
        from pytorch_distributed_mnist_tpu.distrib.cas import (
            load_manifest_arrays,
        )

        manifest, arrays = load_manifest_arrays(path)
        new_state = _restore_onto_template(
            path, manifest["leaf_names"], arrays, state)
        return new_state, int(manifest["epoch"]), float(manifest["best_acc"])
    with np.load(path) as z:
        meta = json.loads(bytes(z["__meta__"]).decode())
        saved = [z[f"leaf_{i}"] for i in range(len(meta["leaf_names"]))]
    new_state = _restore_onto_template(path, meta["leaf_names"], saved, state)
    return new_state, int(meta["epoch"]), float(meta["best_acc"])


def read_checkpoint_arrays(path: str) -> Tuple[Dict[str, Any], list]:
    """``(meta, host arrays in leaf_names order)`` for ANY layout — npz
    file, sharded ``.ckpt`` dir (stitched), or manifest (assembled) —
    with no template state: the byte-level read the delta publish
    converter (``distrib/publish.py::publish_from_checkpoint``) and the
    round-trip tests build on."""
    if os.path.isdir(path):
        return _stitch_sharded(path)
    if path.endswith(".manifest"):
        from pytorch_distributed_mnist_tpu.distrib.cas import (
            load_manifest_arrays,
        )

        return load_manifest_arrays(path)
    with np.load(path) as z:
        meta = json.loads(bytes(z["__meta__"]).decode())
        return meta, [z[f"leaf_{i}"]
                      for i in range(len(meta["leaf_names"]))]


def _read_meta(path: str) -> Dict[str, Any]:
    """The checkpoint's meta dict, without touching array bytes — the
    one dir-vs-npz container read behind every inspection gate
    (``checkpoint_parallel_layout``, ``checkpoint_world``), so a meta
    container change lands once."""
    if os.path.isdir(path):
        with open(os.path.join(path, "meta.json")) as f:
            return json.load(f)
    if path.endswith(".manifest"):
        # The manifest IS meta (plus chunk refs): same epoch/world/
        # parallel_layout keys, so every inspection gate reads it as-is.
        with open(path) as f:
            return json.load(f)
    with np.load(path) as z:
        return json.loads(bytes(z["__meta__"]).decode())


def checkpoint_parallel_layout(path: str) -> Optional[Dict[str, Any]]:
    """Read just the ``parallel_layout`` provenance stamp from a
    checkpoint's meta — no array bytes touched, so the serve boot/reload
    layout gate can run before (and far cheaper than) the template load.
    Returns ``None`` for checkpoints saved without the stamp (library
    callers, pre-stamp files): no provenance, nothing to contradict."""
    layout = _read_meta(path).get("parallel_layout")
    return dict(layout) if layout is not None else None


def checkpoint_world(path: str) -> Optional[Dict[str, int]]:
    """Read just the saving world's shape (``{"processes": P,
    "devices": D}``) from a checkpoint's meta — no array bytes touched.

    The inspection twin of ``checkpoint_parallel_layout``: the elastic
    resume path and serve boot read it to KNOW a restore is a
    cross-world reshard (and log/record it) instead of discovering
    world provenance from a failed load. Returns ``None`` for
    checkpoints saved before the stamp existed — no provenance, and the
    restore path reshards regardless."""
    world = _read_meta(path).get("world")
    return ({"processes": int(world["processes"]),
             "devices": int(world["devices"])}
            if world is not None else None)


def is_corrupt_checkpoint_error(exc: BaseException) -> bool:
    """True when a ``load_checkpoint`` failure means the FILE is damaged
    (truncated download, torn write, lost shard file) rather than the
    CALLER being wrong (model/optimizer mismatch -> shape/leaf-count
    ValueErrors, path typo on a fresh run).

    The distinction gates resume-time quarantine: a corrupt latest
    checkpoint is renamed ``*.corrupt`` and resume falls back to the
    next-older epoch, while a mismatch must keep aborting loudly —
    quarantining a perfectly good checkpoint because the user changed
    ``--model`` would silently destroy their training history.

    Only CONTENT-level damage qualifies (bytes present but undecodable).
    Absence-level signals — a published ``.ckpt`` directory "missing"
    meta.json or a shard file — are NOT corruption: the atomic publish
    means a published directory was complete when renamed, so a missing
    member at resume time is far more likely a stale NFS attribute/
    readdir cache serving an incomplete view, and quarantining on it
    would destroy the newest good checkpoint. Those abort loudly.
    """
    import zipfile
    import zlib

    if isinstance(exc, (zipfile.BadZipFile, zlib.error, EOFError,
                        json.JSONDecodeError)):
        return True
    if isinstance(exc, KeyError):
        # npz member missing (__meta__/leaf_N): a torn or foreign zip
        # (zip content, not filesystem absence — the file itself decoded).
        return True
    if isinstance(exc, ValueError):
        # np.load on a non-zip is corruption; shape/leaf-count
        # mismatches (and _load_sharded's missing-shards complaint,
        # which is absence-level) are not.
        msg = str(exc)
        return ("Cannot load file" in msg
                or "Failed to interpret" in msg or "allow_pickle" in msg)
    return False


def quarantine_checkpoint(path: str) -> str:
    """Rename a corrupt checkpoint out of the resolution namespace.

    ``checkpoint_{e}.npz`` -> ``checkpoint_{e}.npz.corrupt`` (numbered
    ``.corrupt2``... if a previous quarantine of the same epoch exists),
    for both layouts — ``_epoch_checkpoints``'s pattern cannot match the
    suffix, so ``latest_checkpoint`` falls back to the next-older epoch
    and pruning never touches the evidence. Returns the quarantine path.
    """
    dest = path + CORRUPT_SUFFIX
    n = 2
    while os.path.exists(dest):
        dest = f"{path}{CORRUPT_SUFFIX}{n}"
        n += 1
    os.replace(path, dest)
    return dest


def _epoch_checkpoints(directory: str) -> list:
    """All published per-epoch checkpoints in ``directory`` as sorted
    ``(epoch, path)`` pairs. The single source of the eligibility rule for
    both resume selection and pruning (so they can never disagree about
    what counts as a checkpoint). All three layouts match (``.npz`` file,
    ``.ckpt`` dir, ``.manifest`` delta publish — so manifests ride the
    same resolution, watcher polling, and prune window with no second
    rule); the atomic writers' in-flight ``.tmp`` names never do,
    so a crash mid-save can only ever expose the last *published* file —
    the restart-from-checkpoint recovery model SURVEY.md section 5
    prescribes."""
    if not os.path.isdir(directory):
        return []
    out = []
    for name in os.listdir(directory):
        m = re.fullmatch(r"checkpoint_(\d+)\.(npz|ckpt|manifest)", name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    return sorted(out)


def latest_checkpoint(directory: str) -> Optional[str]:
    """Path of the highest-epoch ``checkpoint_{e}`` in ``directory``, or None.

    Multi-host callers must agree on the result across processes (NFS
    attribute caches can show different listings); ``cli.run`` resolves on
    process 0 and broadcasts.
    """
    found = _epoch_checkpoints(directory)
    return found[-1][1] if found else None


def prune_checkpoints(directory: str, keep_last: int) -> None:
    """Delete per-epoch checkpoints strictly older than the latest
    *published* epoch minus ``keep_last``.

    The reference retains every epoch's file with no GC (``:267-268``) and
    so does this framework by default (``keep_last <= 0``); this is the
    opt-in bound for long runs. ``model_best`` copies are never pruned.
    Only process 0 calls this (same gate as the npz write).

    ORDERING GUARANTEE (the serve hot-reload contract,
    ``serve/reload.py``): pruning is keyed off the latest PUBLISHED epoch
    ``L`` and deletes only epochs ``e < L - keep_last`` — the window
    ``[L - keep_last, L]`` always survives. A reload watcher only ever
    starts loading the latest published checkpoint it can see, and
    pruning runs only as part of publishing a newer one, so with
    ``keep_last >= 1`` the checkpoint a watcher is mid-load on stays on
    disk for at least ``keep_last`` further publishes (one full epoch of
    training each) before it can be deleted — a load would have to
    straddle ``keep_last`` whole epochs to race the GC. A count-based
    "keep the N newest files" rule (the pre-serving behavior) has no such
    bound: publish + prune could delete the previous latest at the exact
    moment a watcher opened it.
    """
    if keep_last <= 0:
        return
    found = _epoch_checkpoints(directory)
    if not found:
        return
    latest_epoch = found[-1][0]
    for epoch, path in found:
        if epoch >= latest_epoch - keep_last:
            break  # sorted: everything from here on is inside the window
        if os.path.isdir(path):
            shutil.rmtree(path)
        else:
            os.remove(path)


class AsyncCheckpointer:
    """Overlap checkpoint file I/O with the next epoch's compute.

    ``save()`` snapshots every leaf (npz layout) or every OWNED shard
    (sharded layout) to host memory synchronously — the only part that
    must see a consistent device state; the train loop is free to
    donate/overwrite buffers the moment it returns — then runs the file
    writes on a single worker thread. ``wait()`` joins the in-flight
    write; it is called before the next ``save`` (one write in flight at
    most, so a slow disk can delay training by at most one checkpoint),
    at context exit, and returns the last written path.

    Sharded (multi-host) layout: the layout's correctness barriers are
    device collectives, and running those on a side thread while the
    main thread launches train steps could interleave two collective
    programs — a deadlock. So the phases split (Orbax-style commit):
    tmp-dir prepare (barrier) + shard snapshot run inline in ``save()``,
    the shard/index/meta file writes run on the writer thread, and the
    publish barrier + atomic rename run at the NEXT main-thread drain
    point (the next ``save()`` or the context exit). Every process
    drains at the same logical step, so the deferred collectives match.
    Net effect: epoch N's directory is published at epoch N+1's save —
    a crash loses at most the one unpublished write, the same guarantee
    the async npz path gives for its in-flight file.
    """

    def __init__(self) -> None:
        self._thread = None
        self._result: Optional[str] = None
        self._error: Optional[BaseException] = None
        self._pending_publish: Optional[Dict[str, Any]] = None

    def save(self, state, **kwargs) -> None:
        self.wait()
        # Arm fresh: from here on _result must only ever hold THIS save's
        # outcome. Without this, a failed write/publish leaves the
        # PREVIOUS epoch's path in _result, and a later wait() (e.g.
        # after the caller caught the error) would return that stale path
        # as if it were the latest save's (round-5 advisor).
        self._result = None
        named = _leaves_with_names(_state_tree(state))
        layout = kwargs.pop("layout", None)
        if layout not in (None, "npz", "sharded"):
            raise ValueError(f"unknown checkpoint layout {layout!r}")
        if kwargs.get("publish") == "delta":
            # The async delta path rides the npz machinery below: a
            # pid-0 host snapshot inline, chunking + manifest write on
            # the writer thread (``save_checkpoint`` routes on the
            # ``publish`` kwarg it keeps in ``kwargs``). Sharded states
            # must fail HERE — silently falling through to the sharded
            # layout would drop the requested delta publish.
            if layout == "sharded" or not all(
                _npz_saveable(v) for _, v in named
            ):
                raise ValueError(
                    "--publish delta requires fully-addressable (or "
                    "replicated) leaves; save the sharded layout and "
                    "convert via publish_from_checkpoint")
        elif layout == "sharded" or (
            layout is None and not all(_npz_saveable(v) for _, v in named)
        ):
            self._save_sharded_async(named, kwargs)
            return
        pid = kwargs.get("process_index")
        if (jax.process_index() if pid is None else pid) != 0:
            # npz saves are process-0-only; snapshotting a full host copy
            # of params+moments (and spawning a thread) on every other
            # host would buy nothing but RAM pressure.
            self._result = None
            return
        host_state = jax.tree.map(np.asarray, _state_tree(state))
        snapshot = _HostState(host_state)

        def _write() -> None:
            try:
                # Annotated on THIS thread's timeline: the main thread's
                # "checkpoint_drain" span only covers waiting for us.
                with jax.profiler.TraceAnnotation(
                    "checkpoint_async_write", epoch=kwargs.get("epoch", -1)
                ):
                    self._result = save_checkpoint(snapshot, **kwargs)
            except BaseException as exc:  # surfaced by the next wait()
                self._error = exc

        import threading

        self._thread = threading.Thread(target=_write, daemon=True)
        self._thread.start()

    def _save_sharded_async(self, named, kwargs: Dict[str, Any]) -> None:
        epoch = kwargs["epoch"]
        directory = kwargs.get("directory", CHECKPOINT_DIR)
        pid = kwargs.get("process_index")
        pid = jax.process_index() if pid is None else pid
        # Phases 1-2 inline: the tmp-clean barrier (collective) and the
        # owned-shard D2H snapshot (device reads).
        tmp, final = _sharded_prepare(directory, epoch, pid)
        # Phase 4 bookkeeping is armed EVEN when the inline snapshot
        # below fails: the next drain's write-ok agreement then fails
        # every host together, instead of this host raising alone while
        # its peers wait at that drain's collective forever (the same
        # strand class _agree_phase_ok closes for write failures).
        pending = dict(
            tmp=tmp, final=final, directory=directory, epoch=epoch,
            is_best=kwargs.get("is_best", False),
            keep_last=kwargs.get("keep_last", 0), pid=pid,
        )
        try:
            os.makedirs(tmp, exist_ok=True)  # this host's view of the dir
            payload, index = _sharded_collect(named, pid)
            meta = (_sharded_meta(named, epoch, kwargs["best_acc"],
                                  kwargs.get("parallel_layout"))
                    if pid == 0 else None)
        except Exception as exc:
            self._error = exc
            self._pending_publish = pending
            return

        def _write() -> None:
            try:
                with jax.profiler.TraceAnnotation(
                    "checkpoint_async_write", epoch=epoch
                ):
                    _sharded_write_files(tmp, pid, payload, index, meta)
            except BaseException as exc:  # surfaced by the next wait()
                self._error = exc

        # Phase 4 runs at the next drain, on the main thread.
        self._pending_publish = pending
        import threading

        self._thread = threading.Thread(target=_write, daemon=True)
        self._thread.start()

    def wait(self) -> Optional[str]:
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._pending_publish is not None:
            pub, self._pending_publish = self._pending_publish, None
            err, self._error = self._error, None
            # Every host drains at the same logical step, so the
            # agreement collective lines up; it raises (on every host)
            # when any host's write failed, leaving the tmp dir for
            # postmortem and the publish barrier unentered.
            _agree_phase_ok(err, pub["epoch"], "write",
                            f"dropping unpublished {pub['tmp']}")
            self._result = _sharded_publish(**pub)
        if self._error is not None:
            exc, self._error = self._error, None
            raise exc
        return self._result

    def __enter__(self) -> "AsyncCheckpointer":
        return self

    def __exit__(self, *exc_info) -> None:
        # Swallow nothing: a failed in-flight write must fail the run,
        # unless the body is already unwinding on its own exception.
        if exc_info[0] is None:
            self.wait()
        else:
            from pytorch_distributed_mnist_tpu.runtime import supervision
            from pytorch_distributed_mnist_tpu.utils.profiling import (
                failure_events,
            )

            if self._thread is not None:
                self._thread.join()
                self._thread = None
            if self._error is not None:
                # The with-body is unwinding on its own exception, which
                # must not be masked — but a silently dropped write error
                # makes the lost checkpoint invisible to postmortems
                # (round-4 advisor). Say what failed before discarding.
                print(
                    "WARNING: async checkpoint write failed while the "
                    f"run was unwinding; the write error is discarded in "
                    f"favor of the run's own exception: {self._error!r}",
                    file=sys.stderr,
                )
                failure_events.record(
                    "async_write_error_discarded", repr(self._error))
                self._error = None
            if self._pending_publish is not None:
                # Never run the deferred publish barrier while unwinding:
                # a PEER failure (or watchdog abort) means the other
                # hosts are unwinding too and would never arrive. The
                # unpublished tmp dir is named so the epoch's loss is
                # visible, not silent.
                print(
                    "WARNING: unpublished checkpoint "
                    f"{self._pending_publish['tmp']} dropped during "
                    "unwind (publish barrier skipped)",
                    file=sys.stderr,
                )
                failure_events.record(
                    "pending_publish_dropped", self._pending_publish["tmp"])
                self._pending_publish = None
            # The agreed exit (ADVICE.md residual hazard, now closed):
            # a HOST-LOCAL failure must not let this host vanish while
            # its peers proceed to the next drain's write agreement and
            # block forever in it. Delivering the poison pill here —
            # inside the saver's scope boundary — covers every
            # AsyncCheckpointer user, not just cli.run (whose supervised
            # scope calls this too; delivery is idempotent per
            # exception, so the pill goes out exactly once).
            supervision.deliver_poison(exc_info[1])


class _HostState:
    """Duck-typed stand-in for a TrainState whose leaves are host arrays:
    exactly the attributes ``_state_tree`` reads, nothing else."""

    def __init__(self, tree: Dict[str, Any]) -> None:
        self.params = tree["params"]
        self.opt_state = tree["opt_state"]
        self.step = tree["step"]


def try_resume(path: str, state) -> Tuple[Any, int, float]:
    """Reference resume policy (``:197-214``): load if the file exists, else
    warn and continue fresh with ``(state, 0, 0.0)``.

    ``path == 'auto'`` resolves to the newest checkpoint in the run's
    checkpoint directory (see ``cli.py``) — the restart-after-preemption
    mode: the same command line works for the first launch (no checkpoint
    yet, trains fresh) and every relaunch (continues where it died).
    """
    if path and (os.path.isfile(path) or os.path.isdir(path)):
        state, start_epoch, best_acc = load_checkpoint(path, state)
        print(f"=> loaded checkpoint '{path}' (epoch {start_epoch})")
        return state, start_epoch, best_acc
    if path:
        print(f"=> no checkpoint found at '{path}'")
    return state, 0, 0.0
