"""Jitted train/eval step factories (GSPMD auto-sharded path).

Replaces the reference's per-batch hot loop
(``/root/reference/multi_proc_single_gpu.py:83-95``): H2D copy, forward,
``F.cross_entropy``, ``zero_grad``/``backward``/``step``, plus two
``.item()`` host syncs per batch. Here the whole of that is ONE compiled XLA
program per batch — forward, loss, backward, gradient AllReduce (inserted by
sharding propagation), Adam update, and metric accumulation fused together,
with the input state donated so parameter buffers are updated in place.

``make_train_epoch`` goes further than the reference can: it ``lax.scan``s
the step over an epoch's worth of pre-staged batches, so an entire epoch is
a single device program with zero host round-trips (SURVEY.md section 3.2
names the reference's per-batch ``.item()`` syncs as the anti-pattern).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_mnist_tpu.ops.loss import cross_entropy
from pytorch_distributed_mnist_tpu.ops.metrics import MetricState, metrics_init, metrics_update


def _forward_with_aux(state, params, images, aux_weight: float):
    """Training forward returning ``(logits, aux)`` where ``aux`` is the
    sum of the ``aux_loss`` entries the model sowed under
    ``intermediates`` (the MoE router's load-balance term, models/moe.py)
    — 0.0 when ``aux_weight`` is 0, in which case the capture is skipped
    entirely and the program is byte-identical to the plain path.

    Only leaves whose key is literally ``aux_loss`` enter the objective;
    any other sown intermediate raises, so a future diagnostic sow can
    never silently join the loss. The aux statistic is computed by the
    model over the full static batch — it cannot see the validity mask —
    so it assumes fully-valid train batches, which the train loader
    guarantees (``drop_last=train``, data/loader.py: the ragged tail is
    dropped, never padded; only EVAL batches pad, and eval never runs
    this path)."""
    if not aux_weight:
        return state.apply_fn(params, images, train=True), 0.0
    logits, mods = state.apply_fn(
        params, images, train=True, mutable=["intermediates"]
    )
    aux = jnp.float32(0.0)
    for path, leaf in jax.tree_util.tree_leaves_with_path(mods):
        names = [getattr(k, "key", getattr(k, "name", None)) for k in path]
        if "aux_loss" not in names:
            raise ValueError(
                f"aux_weight is set but the model sowed a non-aux_loss "
                f"intermediate at {jax.tree_util.keystr(path)}; only "
                f"'aux_loss' entries may join the training objective"
            )
        aux = aux + jnp.sum(leaf)
    return logits, aux


def _train_step(state, batch, aux_weight: float = 0.0):
    """One optimizer step on one (global) batch. Pure; jitted by the factory.

    The objective is ``cross_entropy + aux_weight * sown_aux``; metrics
    report the cross-entropy alone so loss curves stay comparable with
    the reference (which has no auxiliary terms, ``:88``)."""
    mask = batch.get("mask")

    def loss_fn(params):
        logits, aux = _forward_with_aux(
            state, params, batch["image"], aux_weight)
        ce = cross_entropy(logits, batch["label"], mask)
        return ce + aux_weight * aux, (ce, logits)

    (_, (loss, logits)), grads = jax.value_and_grad(
        loss_fn, has_aux=True)(state.params)
    new_state = state.apply_gradients(grads)
    metrics = metrics_update(metrics_init(), loss, logits, batch["label"], mask)
    return new_state, metrics


def make_accum_train_step_fn(accum: int, aux_weight: float = 0.0):
    """Pure ``step(state, batch)`` with ``accum``-way gradient accumulation.

    The batch splits into ``accum`` equal micro-batches along dim 0; a
    ``lax.scan`` runs forward+backward per micro-batch against the SAME
    params, accumulating per-example-SUM gradients, then one optimizer
    step applies the example-weighted mean — exactly the full-batch
    gradient (bitwise up to summation order), so DDP loss-mean semantics
    are preserved for any mask distribution across micro-batches. Peak
    activation memory drops by ~``accum`` while the optimizer cadence
    matches the reference's one-step-per-batch loop (``:90-92``).

    ``aux_weight``: the sown-aux objective term (see ``_train_step``).
    Under accumulation each micro-batch's aux is weighted by its example
    count — the example-weighted mean of micro-batch aux values, an
    approximation of the full-batch aux (the router's load fractions are
    per-micro-batch statistics), standard for MoE grad accumulation.
    """
    if accum < 2:
        return functools.partial(_train_step, aux_weight=aux_weight)

    def step(state, batch):
        b = batch["image"].shape[0]
        if b % accum:
            raise ValueError(
                f"global batch {b} not divisible by --grad-accum {accum}"
            )
        micro = jax.tree_util.tree_map(
            lambda v: v.reshape((accum, b // accum) + v.shape[1:]), batch
        )

        def body(carry, mb):
            g_acc, m_acc = carry
            mask = mb.get("mask")
            n = (jnp.sum(mask.astype(jnp.float32)) if mask is not None
                 else jnp.asarray(float(mb["label"].shape[0])))

            def loss_fn(params):
                logits, aux = _forward_with_aux(
                    state, params, mb["image"], aux_weight)
                # per-example SUM: micro-means weighted by real count so
                # the accumulated gradient equals the full-batch gradient
                # even when eval-style masks straddle micro-batches.
                ce_sum = cross_entropy(logits, mb["label"], mask) * n
                return ce_sum + aux_weight * aux * n, (ce_sum, logits)

            (_, (loss_sum_mb, logits)), g = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state.params)
            g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
            loss_mean = loss_sum_mb / jnp.maximum(n, 1.0)
            m_acc = metrics_update(m_acc, loss_mean, logits, mb["label"], mask)
            return (g_acc, m_acc), None

        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(jnp.shape(p), jnp.result_type(p)), state.params
        )
        (grads_sum, metrics), _ = lax.scan(
            body, (zeros, metrics_init()), micro
        )
        total = jnp.maximum(metrics.count, 1.0)
        grads = jax.tree_util.tree_map(lambda g: g / total, grads_sum)
        return state.apply_gradients(grads), metrics

    return step


def make_forward_program(apply_fn):
    """``forward(params, images) -> logits`` — the ONE inference forward
    pass, shared by the ``-e/--evaluate`` eval step below and the serving
    engine's bucketed AOT programs (``serve/engine.py``).

    Both consumers trace exactly this function (``train=False``, params as
    an explicit argument), so evaluate and serve cannot disagree on the
    forward math or dtype policy — ``tests/test_serve_engine.py`` pins
    their logits equal. Params are an argument rather than a closure
    capture so the serve engine can hot-swap checkpoints without
    invalidating its compiled executables (the no-recompile invariant).

    How it spans devices is NOT decided here: the serve-side program
    registry (``serve/programs.py``) lowers this same function per
    model x serve-mode — single-device, or pjit over a tensor/expert
    serving mesh with shardings derived from the training rule tables —
    which is what keeps every serving plane's math pinned to eval's.
    """

    def forward(params, images):
        return apply_fn(params, images, train=False)

    return forward


def _eval_step(state, batch):
    """Forward + metrics, no gradient (reference ``evaluate``, ``:99-116``).

    The batch's validity mask keeps padded examples out of the counts, so a
    sharded eval reports exact whole-dataset metrics (the reference instead
    evaluates the full set redundantly on every rank, ``:143-144``)."""
    mask = batch.get("mask")
    logits = make_forward_program(state.apply_fn)(state.params, batch["image"])
    loss = cross_entropy(logits, batch["label"], mask)
    return metrics_update(metrics_init(), loss, logits, batch["label"], mask)


def _shardings(mesh: Optional[Mesh], axis: str):
    if mesh is None:
        return None, None
    from pytorch_distributed_mnist_tpu.parallel.mesh import resolve_data_axis

    # Hierarchical (DCN x ICI) meshes have no literal 'data' axis: the
    # batch shards over the composed ('dcn', 'ici') pair instead.
    axis = resolve_data_axis(mesh, axis)
    repl = NamedSharding(mesh, P())
    data = NamedSharding(mesh, P(axis))
    return repl, data


def make_train_step(
    mesh: Optional[Mesh] = None, axis: str = "data", state_sharding=None,
    grad_accum: int = 1, aux_weight: float = 0.0,
):
    """Jitted ``step(state, batch) -> (state, MetricState)``.

    With a mesh: state replicated (or laid out per ``state_sharding`` — e.g.
    the tensor-parallel pytree from ``parallel/tensor.py``), batch sharded
    on ``axis`` — XLA's sharding propagation turns the gradient reduction
    into an AllReduce over ICI, the TPU equivalent of DDP's NCCL allreduce
    (``:188-189``). Without a mesh: plain single-device jit (the
    reference's world-size-1 mode). ``grad_accum > 1`` scans that many
    micro-batches before the single optimizer step
    (``make_accum_train_step_fn``).
    """
    step_fn = make_accum_train_step_fn(grad_accum, aux_weight)
    repl, data = _shardings(mesh, axis)
    if mesh is None:
        return jax.jit(step_fn, donate_argnums=(0,))
    state_sh = repl if state_sharding is None else state_sharding
    # ``data`` is a prefix sharding: every batch leaf shards on dim 0.
    return jax.jit(
        step_fn,
        donate_argnums=(0,),
        in_shardings=(state_sh, data),
        out_shardings=(state_sh, repl),
    )


def make_eval_step(
    mesh: Optional[Mesh] = None, axis: str = "data", state_sharding=None
):
    """Jitted ``step(state, batch) -> MetricState`` (no state update).

    Unlike the reference — where every rank redundantly evaluates the full
    test set because the test loader never gets a ``DistributedSampler``
    (``:143-144``, SURVEY.md section 3.3) — the eval batch is sharded across
    the mesh too, and the counts reduce with the same AllReduce machinery.
    """
    repl, data = _shardings(mesh, axis)
    if mesh is None:
        return jax.jit(_eval_step)
    state_sh = repl if state_sharding is None else state_sharding
    return jax.jit(
        _eval_step,
        in_shardings=(state_sh, data),
        out_shardings=repl,
    )


def _take_batch(data, tick):
    """Gather one scan tick's batch from the device-resident dataset."""
    return {
        "image": jnp.take(data["image"], tick["idx"], axis=0),
        "label": jnp.take(data["label"], tick["idx"], axis=0),
        "mask": tick["mask"],
    }


def accumulate_metrics(acc, m):
    """Fold one step's MetricState into a running accumulator — the scan
    bodies' shared reduction, public so the overlapped-ZeRO epoch
    (``parallel/zero_overlap.py``) accumulates with the identical op."""
    return MetricState(
        acc.loss_sum + m.loss_sum,
        acc.correct + m.correct,
        acc.count + m.count,
    )


_accumulate = accumulate_metrics


def _make_epoch(mesh, axis, state_sharding, step_fn, train, indexed):
    """The one epoch builder behind all four make_*_epoch* factories.

    ``train`` selects whether the scan carries (and donates) the state;
    ``indexed`` selects the batch source: pre-staged ``(S, B, ...)``
    arrays, or a device-resident dataset gathered per tick
    (``_take_batch``). Everything else — scan body, metric accumulation,
    jit/sharding wiring — is shared, so the host- and device-gather paths
    cannot drift (tests/test_device_gather.py pins them
    trajectory-identical).
    """

    def scan_epoch(state, batch_of, xs):
        if train:
            def body(carry, x):
                st, acc = carry
                st, m = step_fn(st, batch_of(x))
                return (st, _accumulate(acc, m)), None

            (state, acc), _ = lax.scan(body, (state, metrics_init()), xs)
            return state, acc

        def body(acc, x):
            return _accumulate(acc, _eval_step(state, batch_of(x))), None

        acc, _ = lax.scan(body, metrics_init(), xs)
        return acc

    if indexed:
        def epoch(state, data, ticks):
            return scan_epoch(state, lambda t: _take_batch(data, t), ticks)
    else:
        def epoch(state, batches):
            return scan_epoch(state, lambda b: b, batches)

    repl, _ = _shardings(mesh, axis)
    donate = (0,) if train else ()
    if mesh is None:
        return jax.jit(epoch, donate_argnums=donate)
    from pytorch_distributed_mnist_tpu.parallel.mesh import resolve_data_axis

    state_sh = repl if state_sharding is None else state_sharding
    xs_shard = NamedSharding(
        mesh, P(None, resolve_data_axis(mesh, axis)))  # (steps, batch) prefix
    in_sh = ((state_sh, repl, xs_shard) if indexed
             else (state_sh, xs_shard))
    out_sh = (state_sh, repl) if train else repl
    return jax.jit(
        epoch, donate_argnums=donate, in_shardings=in_sh,
        out_shardings=out_sh,
    )


def make_train_epoch(
    mesh: Optional[Mesh] = None, axis: str = "data", state_sharding=None,
    grad_accum: int = 1, aux_weight: float = 0.0,
):
    """Jitted ``epoch(state, batches) -> (state, MetricState)`` via lax.scan.

    ``batches`` is a dict of arrays with a leading steps axis:
    ``image: (S, B, ...)``, ``label: (S, B)``; the batch axis B is sharded on
    the mesh. The whole epoch runs as one XLA program — S fused train steps
    with on-device metric accumulation, one host sync at the end.
    ``state_sharding`` overrides the replicated state layout (TP tables from
    ``parallel/tensor.py``, ZeRO-1 from ``parallel/zero.py``).
    """
    return _make_epoch(mesh, axis, state_sharding,
                       make_accum_train_step_fn(grad_accum, aux_weight),
                       train=True, indexed=False)


def make_train_epoch_indexed(
    mesh: Optional[Mesh] = None, axis: str = "data", state_sharding=None,
    grad_accum: int = 1, aux_weight: float = 0.0,
):
    """Jitted ``epoch(state, data, ticks) -> (state, MetricState)`` where
    the per-step batch is gathered ON DEVICE.

    ``data`` is the whole dataset resident on device ({'image': (N, ...),
    'label': (N,)}, replicated); ``ticks`` is {'idx': (S, B) int32,
    'mask': (S, B)} with B sharded on the mesh. Each scan tick does a
    ``jnp.take`` of its rows — so the dataset crosses the host boundary
    once per RUN and the per-epoch upload is the ~KB index matrix, not a
    full permuted copy of the dataset (the host-gather path's cost, which
    the reference hides behind DataLoader workers,
    ``/root/reference/multi_proc_single_gpu.py:156``). Device memory also
    drops: one (B, ...) batch materializes per tick instead of the staged
    (S, B, ...) epoch.

    Measured on chip (round 3, ``tools/captured/bench.json``) this path
    is ~10% SLOWER than host-gather on the MNIST CNN (337,085 vs 375,868
    img/s/chip) — the random-row HBM gather costs more than the staged
    epoch's one upload saves at this dataset size. It is therefore the
    documented memory/host-bandwidth saver, NOT the throughput default
    (``--epoch-gather host`` everywhere since round 5); ``bench.py``'s
    sorted-index secondary probes whether gather locality (sort indices
    within a tick) closes the gap.
    """
    return _make_epoch(mesh, axis, state_sharding,
                       make_accum_train_step_fn(grad_accum, aux_weight),
                       train=True, indexed=True)


def make_eval_epoch(
    mesh: Optional[Mesh] = None, axis: str = "data", state_sharding=None
):
    """Jitted ``epoch(state, batches) -> MetricState`` via lax.scan.

    No device-gather twin on purpose: the eval set never reshuffles, so
    the Trainer stages its sharded epoch on device once and reuses it —
    already zero per-pass host work, without replicating the test set
    into every device's HBM the way a resident-dataset gather would.
    """
    return _make_epoch(mesh, axis, state_sharding, None,
                       train=False, indexed=False)


def abstract_spec(tree):
    """``jax.ShapeDtypeStruct`` pytree mirroring ``tree``'s array leaves —
    the abstract argument form every ``precompile`` call lowers against.
    Works on concrete jax arrays, NumPy arrays, and existing specs alike;
    only shape/dtype are read, so building a spec from the full dataset
    costs nothing."""
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x)),
        tree,
    )


def precompile(fn, *abstract_args, program: str = "program"):
    """AOT-compile a jitted step/epoch program on abstract shapes.

    ``fn.lower(*abstract_args).compile()`` runs the whole pipeline —
    trace, lower, XLA backend compile (or persistent-cache fetch) — ahead
    of the first real batch, off the critical path: the Trainer calls
    this from background threads while MNIST staging/host-gather runs on
    the main thread. The returned ``Compiled`` executable is the SAME
    program the first real call would build (tests pin the trajectories
    bit-identical) and is used directly by the Trainer, so the first step
    triggers zero further compiles — in-process reuse, no re-lowering,
    no cache round-trip.

    Compile wall-ms, XLA backend-compile count, and persistent-cache
    hit/miss land in ``utils.profiling.compile_log`` under ``program``.
    """
    from pytorch_distributed_mnist_tpu.utils.profiling import compile_log

    with compile_log.measure(program):
        return fn.lower(*abstract_args).compile()
