"""Training state pytree.

Replaces the reference's scattered per-process mutable state — DDP-wrapped
``model`` + ``optimizer`` objects plus loose ``start_epoch`` / ``best_acc``
globals (``/root/reference/multi_proc_single_gpu.py:163-214``) — with one
immutable pytree that a jitted, donated ``train_step`` threads through the
epoch loop. ``epoch`` and ``best_acc`` live on the host side of the
checkpoint schema (see ``train/checkpoint.py``), matching the reference's
checkpoint dict (``:250-255``).

The optimizer is optax Adam with the reference's default ``lr=1e-3``
(``:191``), wrapped in ``inject_hyperparams`` so the per-epoch step-decay LR
(``:257-261``) is a plain float written into ``opt_state.hyperparams`` —
no re-jit when the LR changes.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.struct
import jax
import jax.numpy as jnp
import optax


@flax.struct.dataclass
class TrainState:
    """Immutable training state threaded through the jitted step."""

    step: jnp.ndarray  # i32 scalar, global step counter
    params: Any  # model parameter pytree
    opt_state: Any  # optax state (holds hyperparams.learning_rate)
    apply_fn: Callable = flax.struct.field(pytree_node=False)
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)

    def apply_gradients(self, grads):
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(step=self.step + 1, params=new_params, opt_state=new_opt_state)

    @property
    def learning_rate(self) -> float:
        return float(self.opt_state.hyperparams["learning_rate"])

    def with_learning_rate(self, lr: float) -> "TrainState":
        """Return state with the injected LR replaced (device-side, no re-jit)."""
        hyper = dict(self.opt_state.hyperparams)
        hyper["learning_rate"] = jnp.asarray(lr, jnp.float32)
        return self.replace(opt_state=self.opt_state._replace(hyperparams=hyper))


def make_optimizer(
    lr: float = 1e-3,
    optimizer: str = "adam",
    momentum: float = 0.9,
    weight_decay: float = 1e-4,
) -> optax.GradientTransformation:
    """Build the optimizer.

    ``adam`` with lr=1e-3 is the reference's active choice (``:191``); ``sgd``
    with momentum+weight-decay mirrors its commented-out alternative
    (``:192-194``) so the ``--momentum`` / ``--wd`` flags are functional here
    rather than dead as in the reference (SURVEY.md section 5 config notes).
    """
    if optimizer == "adam":
        return optax.inject_hyperparams(optax.adam)(learning_rate=lr)
    if optimizer == "adam_pallas":
        # Same state layout as adam (count/mu/nu) but the update is the
        # fused Pallas kernel (ops/pallas/adam.py) — checkpoint-compatible.
        from pytorch_distributed_mnist_tpu.ops.pallas.adam import pallas_adam

        return optax.inject_hyperparams(pallas_adam)(learning_rate=lr)
    if optimizer == "sgd":

        def sgd_wd(learning_rate):
            return optax.chain(
                optax.add_decayed_weights(weight_decay),
                optax.sgd(learning_rate, momentum=momentum),
            )

        return optax.inject_hyperparams(sgd_wd)(learning_rate=lr)
    raise ValueError(f"unknown optimizer {optimizer!r}")


def create_train_state(
    model,
    rng: jax.Array,
    input_shape=(1, 28, 28, 1),
    lr: float = 1e-3,
    optimizer: str = "adam",
    momentum: float = 0.9,
    weight_decay: float = 1e-4,
) -> TrainState:
    """Initialize params (float32) and optimizer state for ``model``."""
    params = model.init(rng, jnp.zeros(input_shape, jnp.float32))
    tx = make_optimizer(lr, optimizer, momentum, weight_decay)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        apply_fn=model.apply,
        tx=tx,
    )
