"""Training engine: state, jitted steps, trainer, LR schedule, checkpointing."""

from pytorch_distributed_mnist_tpu.train.state import TrainState, create_train_state
from pytorch_distributed_mnist_tpu.train.lr_schedule import step_decay_schedule
from pytorch_distributed_mnist_tpu.train.trainer import Trainer

__all__ = ["TrainState", "create_train_state", "step_decay_schedule", "Trainer"]
