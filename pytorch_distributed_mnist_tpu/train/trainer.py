"""Training engine.

API parity with the reference ``Trainer``
(``/root/reference/multi_proc_single_gpu.py:68-116``): construct with model
state + train/test loaders, then ``train()`` / ``evaluate()`` each run one
pass and return ``(Average, Accuracy)`` meters — same return contract as
``:96-97`` / ``:115-116``.

The execution model is TPU-first rather than a translation:

- the reference's per-batch sequence (H2D copy, forward, loss, backward +
  DDP allreduce, Adam step, two ``.item()`` syncs — ``:83-95``) is one
  donated jitted program per batch;
- ``mode='scan'`` (default when the dataset is device-resident) stages the
  whole epoch and runs it as a single ``lax.scan`` program — zero host
  round-trips per epoch;
- ``mode='explicit'`` uses the shard_map/psum step from
  ``parallel/collectives.py`` — the auditable direct DDP analog;
- metrics accumulate on device (``ops/metrics.py``) and transfer once per
  pass.
"""

from __future__ import annotations

from typing import Optional, Tuple

from jax.sharding import Mesh

from pytorch_distributed_mnist_tpu.data.loader import MNISTDataLoader, make_global_batch
from pytorch_distributed_mnist_tpu.ops.metrics import Accuracy, Average, MetricState
from pytorch_distributed_mnist_tpu.parallel.collectives import make_explicit_dp_train_step
from pytorch_distributed_mnist_tpu.train.state import TrainState
from pytorch_distributed_mnist_tpu.train.steps import (
    make_eval_epoch,
    make_eval_step,
    make_train_epoch,
    make_train_step,
)


def _meters(ms: Optional[MetricState]) -> Tuple[Average, Accuracy]:
    """One device->host sync: fold a MetricState into parity meter objects.

    ``None`` (an empty loader produced zero batches) yields empty meters,
    matching the reference meters' zero-division guard (``:37-39, 55-57``).
    """
    loss, acc = Average(), Accuracy()
    count = 0 if ms is None else int(ms.count)
    if count:
        loss.update(float(ms.loss_sum) / count, count)
        acc.update(int(ms.correct), count)
    return loss, acc


class Trainer:
    """Runs train/eval passes of jitted steps over sharded batches."""

    def __init__(
        self,
        state: TrainState,
        train_loader: MNISTDataLoader,
        test_loader: MNISTDataLoader,
        mesh: Optional[Mesh] = None,
        mode: str = "scan",
        state_sharding=None,
        grad_accum: int = 1,
    ) -> None:
        if mode not in ("scan", "stepwise", "explicit"):
            raise ValueError(f"unknown trainer mode {mode!r}")
        if state_sharding is not None and mesh is None:
            raise ValueError("state_sharding requires a mesh")
        self.state = state
        self.train_loader = train_loader
        self.test_loader = test_loader
        self.mesh = mesh
        self.mode = mode
        if mode == "explicit":
            if mesh is None:
                raise ValueError("mode='explicit' requires a mesh")
            if state_sharding is not None:
                raise ValueError(
                    "mode='explicit' is the replicated-DP shard_map path; "
                    "use scan/stepwise with a sharded state"
                )
            if grad_accum > 1:
                raise ValueError(
                    "mode='explicit' does not support grad_accum; use "
                    "scan/stepwise"
                )
            self._train_step = make_explicit_dp_train_step(mesh)
            # Explicit end to end: the eval step must be shard_map too, or
            # eval would silently run the auto-GSPMD path beside the
            # explicit train step (and with the fused pallas loss, gather
            # the batch the shard_map body otherwise keeps local).
            from pytorch_distributed_mnist_tpu.parallel.collectives import (
                make_explicit_dp_eval_step,
            )

            self._eval_step = make_explicit_dp_eval_step(mesh)
        else:
            self._train_step = make_train_step(
                mesh, state_sharding=state_sharding, grad_accum=grad_accum
            )
            self._eval_step = make_eval_step(mesh, state_sharding=state_sharding)
        self._train_epoch = (
            make_train_epoch(mesh, state_sharding=state_sharding,
                             grad_accum=grad_accum)
            if mode == "scan" else None
        )
        self._eval_epoch = (
            make_eval_epoch(mesh, state_sharding=state_sharding)
            if mode == "scan" else None
        )

    def train(self) -> Tuple[Average, Accuracy]:
        """One training epoch; returns (loss meter, accuracy meter).

        Parity contract: reference ``Trainer.train`` (``:77-97``).
        """
        if self.mode == "scan":
            batches = make_global_batch(
                self.train_loader.stacked_epoch(), self.mesh, leading_replicated=True
            )
            self.state, ms = self._train_epoch(self.state, batches)
        else:
            ms = None
            for batch in self.train_loader:
                gbatch = make_global_batch(batch, self.mesh)
                self.state, m = self._train_step(self.state, gbatch)
                ms = m if ms is None else MetricState(
                    ms.loss_sum + m.loss_sum, ms.correct + m.correct, ms.count + m.count
                )
        return _meters(ms)

    def evaluate(self) -> Tuple[Average, Accuracy]:
        """One evaluation pass; returns (loss meter, accuracy meter).

        Parity contract: reference ``Trainer.evaluate`` (``:99-116``). No
        gradient, no state update. When the eval loader is sharded the
        metric reduction crosses devices inside the jitted program.
        """
        if self.mode == "scan":
            batches = make_global_batch(
                self.test_loader.stacked_epoch(), self.mesh, leading_replicated=True
            )
            ms = self._eval_epoch(self.state, batches)
        else:
            ms = None
            for batch in self.test_loader:
                gbatch = make_global_batch(batch, self.mesh)
                m = self._eval_step(self.state, gbatch)
                ms = m if ms is None else MetricState(
                    ms.loss_sum + m.loss_sum, ms.correct + m.correct, ms.count + m.count
                )
        return _meters(ms)
