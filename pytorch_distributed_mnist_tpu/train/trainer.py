"""Training engine.

API parity with the reference ``Trainer``
(``/root/reference/multi_proc_single_gpu.py:68-116``): construct with model
state + train/test loaders, then ``train()`` / ``evaluate()`` each run one
pass and return ``(Average, Accuracy)`` meters — same return contract as
``:96-97`` / ``:115-116``.

The execution model is TPU-first rather than a translation:

- the reference's per-batch sequence (H2D copy, forward, loss, backward +
  DDP allreduce, Adam step, two ``.item()`` syncs — ``:83-95``) is one
  donated jitted program per batch;
- ``mode='scan'`` (default when the dataset is device-resident) stages the
  whole epoch and runs it as a single ``lax.scan`` program — zero host
  round-trips per epoch;
- ``mode='explicit'`` uses the shard_map/psum step from
  ``parallel/collectives.py`` — the auditable direct DDP analog;
- metrics accumulate on device (``ops/metrics.py``) and transfer once per
  pass;
- the scan mode's host-side epoch gather is pipelined: epoch N+1's
  permutation copy runs on a background thread while the device executes
  epoch N (jit dispatch is async), and the eval pass — whose sampler never
  reshuffles — stages its device-resident batches exactly once. The
  reference hides the same cost behind DataLoader worker processes
  (``/root/reference/multi_proc_single_gpu.py:156``); here it leaves the
  critical path entirely.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_mnist_tpu.data.loader import (
    MNISTDataLoader,
    make_global_batch,
    make_replicated,
)
from pytorch_distributed_mnist_tpu.data.staging import BatchFeeder
from pytorch_distributed_mnist_tpu.ops.metrics import Accuracy, Average, MetricState
from pytorch_distributed_mnist_tpu.parallel.collectives import make_explicit_dp_train_step
from pytorch_distributed_mnist_tpu.train.state import TrainState
from pytorch_distributed_mnist_tpu.train.steps import (
    abstract_spec,
    accumulate_metrics,
    make_eval_epoch,
    make_eval_step,
    make_train_epoch,
    make_train_epoch_indexed,
    make_train_step,
    precompile,
)


def _meters(ms: Optional[MetricState]) -> Tuple[Average, Accuracy]:
    """One device->host sync: fold a MetricState into parity meter objects.

    ``None`` (an empty loader produced zero batches) yields empty meters,
    matching the reference meters' zero-division guard (``:37-39, 55-57``).
    """
    loss, acc = Average(), Accuracy()
    count = 0 if ms is None else int(ms.count)
    if count:
        loss.update(float(ms.loss_sum) / count, count)
        acc.update(int(ms.correct), count)
    return loss, acc


class Trainer:
    """Runs train/eval passes of jitted steps over sharded batches."""

    def __init__(
        self,
        state: TrainState,
        train_loader: MNISTDataLoader,
        test_loader: MNISTDataLoader,
        mesh: Optional[Mesh] = None,
        mode: str = "scan",
        state_sharding=None,
        grad_accum: int = 1,
        epoch_gather: str = "host",
        aux_weight: float = 0.0,
        feed_window: int = 2,
        staging_log=None,
        zero_overlap: bool = False,
        zero_level: int = 1,
        zero_bucket_mb: float = 4.0,
        zero_bucket_mb_dcn: float = 0.0,
    ) -> None:
        if mode not in ("scan", "stepwise", "explicit"):
            raise ValueError(f"unknown trainer mode {mode!r}")
        if feed_window < 1:
            raise ValueError(f"feed_window must be >= 1, got {feed_window}")
        if epoch_gather not in ("host", "device"):
            raise ValueError(f"unknown epoch_gather {epoch_gather!r}")
        if epoch_gather == "device" and mode != "scan":
            raise ValueError(
                "epoch_gather='device' is a scan-mode path (the gather "
                "lives inside the scanned epoch program)"
            )
        if state_sharding is not None and mesh is None:
            raise ValueError("state_sharding requires a mesh")
        if zero_overlap:
            # The explicit overlapped-ZeRO data plane
            # (parallel/zero_overlap.py): pure data parallelism with the
            # propagation path's state layout. Host-side composition
            # limits are rejected here (and with flag language in
            # cli.py) rather than discovered as trace errors.
            if mesh is None:
                raise ValueError("zero_overlap requires a mesh")
            if state_sharding is None:
                raise ValueError(
                    "zero_overlap requires the ZeRO state sharding "
                    "(parallel/zero.py shard_state_zero)")
            if mode == "explicit":
                raise ValueError(
                    "zero_overlap does not compose with mode='explicit' "
                    "(both own the mesh as one shard_map data axis)")
            if epoch_gather == "device":
                raise ValueError(
                    "zero_overlap requires epoch_gather='host' (the "
                    "overlapped step is not embedded in the indexed "
                    "device-gather epoch program)")
            if aux_weight:
                raise ValueError(
                    "zero_overlap does not support aux_weight (the sown "
                    "aux statistic is a global-batch quantity; the "
                    "overlapped body sees local shards)")
        self._zero_overlap = zero_overlap
        self._zero_level = zero_level
        self._zero_gather = None
        self._zero_gathered = None
        self.state = state
        self.train_loader = train_loader
        self.test_loader = test_loader
        self.mesh = mesh
        self.mode = mode
        self._state_sharding = state_sharding
        if mode == "explicit":
            if mesh is None:
                raise ValueError("mode='explicit' requires a mesh")
            if state_sharding is not None:
                raise ValueError(
                    "mode='explicit' is the replicated-DP shard_map path; "
                    "use scan/stepwise with a sharded state"
                )
            if grad_accum > 1:
                raise ValueError(
                    "mode='explicit' does not support grad_accum; use "
                    "scan/stepwise"
                )
            if aux_weight:
                raise ValueError(
                    "mode='explicit' does not support aux_weight; use "
                    "scan/stepwise"
                )
            self._train_step = make_explicit_dp_train_step(mesh)
            # Explicit end to end: the eval step must be shard_map too, or
            # eval would silently run the auto-GSPMD path beside the
            # explicit train step (and with the fused pallas loss, gather
            # the batch the shard_map body otherwise keeps local).
            from pytorch_distributed_mnist_tpu.parallel.collectives import (
                make_explicit_dp_eval_step,
            )

            self._eval_step = make_explicit_dp_eval_step(mesh)
        elif zero_overlap:
            from pytorch_distributed_mnist_tpu.parallel.zero_overlap import (
                make_overlap_train_step,
                make_param_gather,
            )

            # Only the programs this mode executes are traced: the scan
            # path never calls the per-batch step. Eval stays on the
            # propagation path — it shares the state layout, and the
            # forward-only program has no weight update to overlap.
            self._train_step = (
                make_overlap_train_step(
                    state, mesh, level=zero_level,
                    bucket_mb=zero_bucket_mb, grad_accum=grad_accum,
                    bucket_mb_dcn=zero_bucket_mb_dcn or None)
                if mode != "scan" else None
            )
            if zero_level == 3:
                self._zero_gather = make_param_gather(mesh)
            self._eval_step = make_eval_step(mesh, state_sharding=state_sharding)
        else:
            self._train_step = make_train_step(
                mesh, state_sharding=state_sharding, grad_accum=grad_accum,
                aux_weight=aux_weight,
            )
            self._eval_step = make_eval_step(mesh, state_sharding=state_sharding)
        self.epoch_gather = epoch_gather
        if mode == "scan" and epoch_gather == "device":
            self._train_epoch = make_train_epoch_indexed(
                mesh, state_sharding=state_sharding, grad_accum=grad_accum,
                aux_weight=aux_weight)
        elif mode == "scan" and zero_overlap:
            from pytorch_distributed_mnist_tpu.parallel.zero_overlap import (
                make_overlap_train_epoch,
            )

            self._train_epoch = make_overlap_train_epoch(
                state, mesh, level=zero_level, bucket_mb=zero_bucket_mb,
                grad_accum=grad_accum,
                bucket_mb_dcn=zero_bucket_mb_dcn or None)
        else:
            self._train_epoch = (
                make_train_epoch(mesh, state_sharding=state_sharding,
                                 grad_accum=grad_accum,
                                 aux_weight=aux_weight)
                if mode == "scan" else None
            )
        # Eval always uses the one-time device staging (_eval_staged):
        # the eval sampler never reshuffles, so the sharded staged epoch
        # already has zero per-pass host work — a device-gather eval would
        # only replicate the test set into every device's HBM for nothing.
        self._eval_epoch = (
            make_eval_epoch(mesh, state_sharding=state_sharding)
            if mode == "scan" else None
        )
        self.staging_log = staging_log
        self.feed_window = feed_window
        # Per-batch input plane (stepwise/explicit): the double-buffered
        # feeder stages batch N+1 (host gather + sharded device_put) on a
        # background thread while the jitted step for batch N executes;
        # window 1 is the inline strict-alternation path, bit-for-bit
        # (data/staging.py; pinned by tests/test_staging.py).
        self._feeder = (
            BatchFeeder(train_loader, mesh, window=feed_window,
                        staging_log=staging_log)
            if mode != "scan" else None
        )
        # Per-batch eval staging cache: the eval sampler never
        # reshuffles, so the staged global batches are identical every
        # pass — gather + device_put them exactly once (the per-batch
        # twin of the scan path's _eval_staged).
        self._eval_staged_batches = None
        # Device-resident train dataset for the device-gather path
        # (uploaded lazily, once per run).
        self._train_data = None
        # Epoch-gather pipelining (scan mode): (epoch, thread, holder) of a
        # background stacked_epoch() for the NEXT epoch, plus the one-time
        # device-resident eval stage. prefetch_enabled exists for the
        # equivalence test that pins prefetched == synchronous trajectories.
        self._prefetch = None
        self.prefetch_enabled = True
        self._eval_staged = None
        # AOT precompile state: program name -> Compiled executable, the
        # threads (by program name) still building them, and any
        # per-program failures (surfaced once at that program's join; the
        # lazy jit path stays the fallback).
        self._precompiled = {}
        self._precompile_threads = {}
        self._precompile_errors = {}
        self._precompile_started = False

    @property
    def state(self):
        return self._state

    @state.setter
    def state(self, value) -> None:
        # Installing a state from outside (resume, per-epoch LR update,
        # tests) invalidates the ZeRO-3 gathered-param carry: the carry
        # is DERIVED state (gathered == allgather(state.params), always)
        # and a stale copy would silently run every forward pass on old
        # weights while the optimizer updates the new shards. The train
        # loops re-derive it lazily (one allgather, off the per-step
        # path) and assign ``_state`` directly when installing a step's
        # own output next to its matching carry.
        self._state = value
        self._zero_gathered = None

    def _start_prefetch(self) -> None:
        """Stage the NEXT epoch's gather while the device runs this one.

        Runs after the epoch program is dispatched (dispatch is async, so
        the chips are already crunching). The gather is the PURE form
        (``stacked_epoch(epoch)``) — the thread never mutates the shared
        sampler, so a concurrent ``set_sample_epoch`` from the caller
        cannot race it. ``train()`` validates the staged epoch against
        the sampler's epoch at consumption time, so a caller that jumps
        epochs (resume) just invalidates the stage — correctness never
        depends on the prediction being right.

        Single-process worlds carry the H2D transfer too: the one big
        ``make_global_batch`` (sharded ``device_put`` of the whole
        stacked epoch) used to run synchronously at the epoch boundary
        even though the host-side stacking was prefetched; now the whole
        stage overlaps the previous epoch's compute and eval. Multi-host
        assembly stays on the main thread — no cross-host-visible array
        work off it (supervision's no-concurrent-collectives rule).
        """
        epoch = self.train_loader.sampler.epoch + 1
        holder = {}

        def work():
            t0 = time.perf_counter()
            staged = self.train_loader.stacked_epoch(epoch)
            t1 = time.perf_counter()
            holder["batches"] = staged
            # Timings only; the staging log is written at CONSUMPTION
            # (train() below), so a prefetch that is discarded — epoch
            # jump, or the run's final fire-and-forget stage — never
            # skews the input-plane story with an epoch nobody used.
            holder["host_ms"] = (t1 - t0) * 1e3
            if jax.process_count() == 1:
                holder["device_batches"] = make_global_batch(
                    staged, self.mesh, leading_replicated=True)
                holder["h2d_ms"] = (time.perf_counter() - t1) * 1e3

        t = threading.Thread(target=work, daemon=True,
                             name="epoch-prefetch")
        t.start()
        self._prefetch = (epoch, t, holder)

    def close(self) -> None:
        """Join and discard any in-flight input-plane thread
        (idempotent): the scan prefetch AND the per-batch feeder.

        The last ``train()`` of a run launches a prefetch nobody will
        consume — and since the stage now carries the full-epoch H2D
        transfer, letting that daemon thread race process teardown means
        a ``device_put`` against a shutting-down runtime and a
        full-epoch device copy held through post-training eval. The
        per-batch feeder has the same hazard when an exception abandons
        ``train()`` mid-epoch: the traceback keeps the generator (and
        its ``finally``) alive until GC, so the feeder must be joined
        explicitly. Callers that finish training (cli.run) close the
        trainer; the staged arrays drop with the holder."""
        if self._prefetch is not None:
            _epoch, t, _holder = self._prefetch
            self._prefetch = None
            t.join()
        if self._feeder is not None:
            self._feeder.close()

    # -- AOT precompile ---------------------------------------------------

    def _precompile_jobs(self):
        """(program name, jitted fn, abstract args) for every program this
        trainer's mode will actually run. Batch specs come from the
        loaders (``data/loader.py batch_spec/epoch_spec/ticks_spec``) so
        they cannot drift from what staging really produces."""
        state_spec = abstract_spec(self.state)
        # Overlapped ZeRO-3 carries the gathered (replicated) params as
        # an explicit argument through the step/epoch boundary.
        carry = ((abstract_spec(self.state.params),)
                 if self._zero_overlap and self._zero_level == 3 else ())
        if self.mode == "scan":
            jobs = [("eval_epoch", self._eval_epoch,
                     (state_spec, self.test_loader.epoch_spec()))]
            if self.epoch_gather == "device":
                data_spec = abstract_spec({
                    "image": self.train_loader.images,
                    "label": self.train_loader.labels,
                })
                jobs.insert(0, (
                    "train_epoch_indexed", self._train_epoch,
                    (state_spec, data_spec, self.train_loader.ticks_spec()),
                ))
            elif self._zero_overlap:
                jobs.insert(0, (
                    "train_epoch_zero_overlap", self._train_epoch,
                    (state_spec,) + carry
                    + (self.train_loader.epoch_spec(),),
                ))
            else:
                jobs.insert(0, ("train_epoch", self._train_epoch,
                                (state_spec, self.train_loader.epoch_spec())))
            return jobs
        if self._zero_overlap:
            return [
                ("train_step_zero_overlap", self._train_step,
                 (state_spec,) + carry + (self.train_loader.batch_spec(),)),
                ("eval_step", self._eval_step,
                 (state_spec, self.test_loader.batch_spec())),
            ]
        suffix = "_explicit" if self.mode == "explicit" else ""
        return [
            ("train_step" + suffix, self._train_step,
             (state_spec, self.train_loader.batch_spec())),
            ("eval_step" + suffix, self._eval_step,
             (state_spec, self.test_loader.batch_spec())),
        ]

    def precompile(self, wait: bool = False) -> None:
        """AOT-compile this trainer's programs on background threads.

        Each program is ``.lower(...).compile()``-d on abstract shapes
        (``train/steps.py precompile``), CONCURRENTLY with whatever the
        caller does next — in ``cli.run`` that is the first epoch's MNIST
        staging/host-gather, so compile leaves the cold-start critical
        path instead of serializing at first use. The compiled
        executables are used directly by ``train()``/``evaluate()`` (no
        re-lowering, no second compile); any failure or signature
        mismatch falls back to the lazy jit path, which is
        trajectory-identical (tests/test_compile_cache.py pins this).

        ``wait=True`` blocks until every program is built — tests and
        callers with nothing to overlap.
        """
        if self._precompile_started:
            return
        self._precompile_started = True
        if self.mesh is not None and self._state_sharding is None \
                and jax.process_count() == 1:
            # Commit the state to the replicated layout the programs are
            # compiled for. Fresh states arrive uncommitted (accepted
            # either way); a resumed state arrives committed to device 0
            # (checkpoint restore) and would otherwise fail the compiled
            # executable's sharding check and recompile lazily. Sharded
            # layouts (TP/ZeRO/PP) are placed by their constructors.
            # Single-process only: a host->multi-host-sharding device_put
            # runs a cross-process value-equality collective (and cannot
            # run at all on the CPU sim); multi-host states stay as they
            # arrive, and a sharding mismatch just takes the lazy path.
            self.state = jax.device_put(
                self.state, NamedSharding(self.mesh, P()))
        for name, fn, specs in self._precompile_jobs():
            def work(name=name, fn=fn, specs=specs):
                try:
                    self._precompiled[name] = precompile(
                        fn, *specs, program=name)
                except Exception as exc:  # noqa: BLE001 - surfaced at join
                    self._precompile_errors[name] = exc

            t = threading.Thread(target=work, daemon=True,
                                 name=f"precompile-{name}")
            t.start()
            self._precompile_threads[name] = t
        if wait:
            self._join_precompile()

    def _join_precompile(self, name: str = None) -> None:
        """Join the thread building ``name`` (all threads when None). Only
        the REQUESTED program blocks the caller: the first train epoch
        must not wait out the eval program's compile — that would
        re-serialize part of the compile time the overlap exists to
        hide; eval's thread keeps compiling during epoch 1 and is joined
        when evaluate() first needs it."""
        names = (list(self._precompile_threads) if name is None
                 else [name] if name in self._precompile_threads else [])
        for n in names:
            self._precompile_threads.pop(n).join()
            exc = self._precompile_errors.pop(n, None)
            if exc is not None:
                print(
                    f"WARNING: precompile of {n} failed; falling back "
                    f"to lazy compilation: {exc!r}",
                    file=sys.stderr, flush=True,
                )

    def _run_program(self, name: str, fn, *args):
        """Run ``name`` via its precompiled executable when one exists and
        matches, else via the lazy jit ``fn`` (identical program)."""
        self._join_precompile(name)
        compiled = self._precompiled.get(name)
        if compiled is not None:
            try:
                return compiled(*args)
            except (TypeError, ValueError) as exc:
                # Shapes/shardings drifted from the precompiled signature
                # (e.g. a mid-run loader swap): drop the stale executable
                # once and let jit recompile for the new signature.
                del self._precompiled[name]
                print(
                    f"WARNING: precompiled {name} no longer matches its "
                    f"arguments; recompiling lazily: {str(exc)[:200]}",
                    file=sys.stderr, flush=True,
                )
        return fn(*args)

    def train(self) -> Tuple[Average, Accuracy]:
        """One training epoch; returns (loss meter, accuracy meter).

        Parity contract: reference ``Trainer.train`` (``:77-97``).
        """
        from pytorch_distributed_mnist_tpu.runtime.supervision import (
            maybe_fault,
        )

        maybe_fault("train_epoch")
        if self.mode == "scan" and self.epoch_gather == "device":
            if self._train_data is None:
                # The dataset crosses the host boundary exactly once.
                self._train_data = make_replicated(
                    {"image": self.train_loader.images,
                     "label": self.train_loader.labels}, self.mesh)
            idx, mask = self.train_loader.epoch_ticks()
            ticks = make_global_batch(
                {"idx": idx.astype(np.int32), "mask": mask}, self.mesh,
                leading_replicated=True)
            self.state, ms = self._run_program(
                "train_epoch_indexed", self._train_epoch,
                self.state, self._train_data, ticks)
        elif self.mode == "scan":
            staged = None
            batches = None
            prefetched_host_ms = None
            if self._prefetch is not None:
                epoch, t, holder = self._prefetch
                self._prefetch = None
                t_wait = time.perf_counter()
                t.join()
                if self.staging_log is not None:
                    self.staging_log.record_wait(
                        (time.perf_counter() - t_wait) * 1e3)
                if epoch == self.train_loader.sampler.epoch:
                    staged = holder.get("batches")
                    if staged is not None:
                        prefetched_host_ms = holder.get("host_ms")
                    batches = holder.get("device_batches")
                    if batches is not None and self.staging_log is not None:
                        self.staging_log.record_stage(
                            host_ms=holder["host_ms"],
                            h2d_ms=holder["h2d_ms"],
                            images=int(staged["label"].size),
                            pipelined=True)
            if batches is None:
                # No (valid) prefetched device stage: do whatever is
                # left on the consumer thread — the whole gather on a
                # cold first epoch, just the H2D in a multi-host world
                # where the thread staged host-side only.
                t0 = time.perf_counter()
                if staged is None:
                    staged = self.train_loader.stacked_epoch()
                t1 = time.perf_counter()
                batches = make_global_batch(
                    staged, self.mesh, leading_replicated=True
                )
                if self.staging_log is not None:
                    t2 = time.perf_counter()
                    if prefetched_host_ms is not None:
                        # Multi-host: the gather DID run on the prefetch
                        # thread (its real wall, not the ~0 ms of the
                        # skipped re-gather above); only the H2D was
                        # inline — the wait below carries exactly that
                        # un-overlapped part, so the overlap fraction
                        # credits the hidden host half and nothing else.
                        self.staging_log.record_stage(
                            host_ms=prefetched_host_ms,
                            h2d_ms=(t2 - t1) * 1e3,
                            images=int(staged["label"].size),
                            pipelined=True)
                    else:
                        self.staging_log.record_stage(
                            host_ms=(t1 - t0) * 1e3, h2d_ms=(t2 - t1) * 1e3,
                            images=int(staged["label"].size),
                            pipelined=False)
                    self.staging_log.record_wait((t2 - t0) * 1e3)
            if self._zero_overlap and self._zero_level == 3:
                # The carried gathered-param copy: step N's tail
                # allgather rides the scan carry into step N+1's
                # forward. Derived state (== allgather(state.params)),
                # rebuilt whenever absent — first epoch, or any outside
                # state install (the state setter invalidates it).
                if self._zero_gathered is None:
                    self._zero_gathered = self._zero_gather(
                        self.state.params)
                new_state, gathered, ms = self._run_program(
                    "train_epoch_zero_overlap", self._train_epoch,
                    self.state, self._zero_gathered, batches)
                self._state = new_state  # direct: keep the matching carry
                self._zero_gathered = gathered
            elif self._zero_overlap:
                self.state, ms = self._run_program(
                    "train_epoch_zero_overlap", self._train_epoch,
                    self.state, batches)
            else:
                self.state, ms = self._run_program(
                    "train_epoch", self._train_epoch, self.state, batches)
            if self.prefetch_enabled:
                self._start_prefetch()
        else:
            ms = None
            carried = self._zero_overlap and self._zero_level == 3
            if carried and self._zero_gathered is None:
                self._zero_gathered = self._zero_gather(self.state.params)
            name = ("train_step_explicit" if self.mode == "explicit"
                    else "train_step_zero_overlap" if self._zero_overlap
                    else "train_step")
            for gbatch in self._feeder.epoch():
                # Per-batch chaos hook: a kill here lands BETWEEN device
                # programs, genuinely mid-epoch — the host-loss shape
                # the elastic runtime (runtime/elastic.py) shrinks
                # around. One dict probe when no fault plan is set.
                maybe_fault("train_step")
                if carried:
                    new_state, gathered, m = self._run_program(
                        name, self._train_step,
                        self.state, self._zero_gathered, gbatch)
                    self._state = new_state  # direct: keep matching carry
                    self._zero_gathered = gathered
                else:
                    self.state, m = self._run_program(
                        name, self._train_step, self.state, gbatch)
                ms = m if ms is None else accumulate_metrics(ms, m)
        return _meters(ms)

    def evaluate(self) -> Tuple[Average, Accuracy]:
        """One evaluation pass; returns (loss meter, accuracy meter).

        Parity contract: reference ``Trainer.evaluate`` (``:99-116``). No
        gradient, no state update. When the eval loader is sharded the
        metric reduction crosses devices inside the jitted program.
        """
        from pytorch_distributed_mnist_tpu.runtime.supervision import (
            maybe_fault,
        )

        maybe_fault("eval")
        if self.mode == "scan":
            if self._eval_staged is None:
                # The eval sampler never reshuffles, so the stacked epoch
                # — and its device placement — is identical every pass:
                # stage it once, host gather and H2D both leave the
                # per-epoch path.
                self._eval_staged = make_global_batch(
                    self.test_loader.stacked_epoch(), self.mesh,
                    leading_replicated=True
                )
            ms = self._run_program(
                "eval_epoch", self._eval_epoch, self.state, self._eval_staged)
        else:
            ms = None
            name = ("eval_step_explicit" if self.mode == "explicit"
                    else "eval_step")
            if self._eval_staged_batches is None:
                # The eval sampler never reshuffles: every pass gathers
                # and device_puts the IDENTICAL batches, so stage them
                # exactly once (the per-batch twin of _eval_staged;
                # only-once staging pinned by tests/test_staging.py).
                self._eval_staged_batches = [
                    make_global_batch(batch, self.mesh)
                    for batch in self.test_loader
                ]
            for gbatch in self._eval_staged_batches:
                m = self._run_program(
                    name, self._eval_step, self.state, gbatch)
                ms = m if ms is None else accumulate_metrics(ms, m)
        return _meters(ms)
