#!/usr/bin/env python3
"""Load generator for the serving endpoint (`tpu-mnist serve`).

Pure stdlib on purpose — no jax, no numpy — so it starts in milliseconds,
runs from any box that can reach the server, and measures the SERVER, not
its own import time. Two disciplines:

- **closed loop** (default): C workers each keep exactly one request in
  flight, back to back — measures throughput at a fixed concurrency and
  the latency that concurrency buys.
- **open loop**: requests fire on a fixed-rate schedule regardless of
  completions — the honest tail-latency discipline (closed-loop
  coordinated omission hides queueing collapse: a slow server slows the
  CLIENTS down). Overload shows up as 503 rejections and p99 growth
  instead of a silently reduced send rate.

Open-loop traffic SHAPES (`--shape`) modulate the rate over the run:
`sine` is the diurnal curve (one period over the duration), `spike` is
a flat baseline with a `--spike-mult`x burst through the middle fifth
(what the autoscaler twin fires at a server), `adversarial` flips
per-second between near-silence and a 3x burst on a seeded RNG — the
worst case for any controller that trusts a trend. Two more shapes
change WHICH BODY each request carries rather than the rate (both run
at the constant rate, and work in closed mode too): `zipf:S` samples
the request template per-request from a Zipf(S) distribution — the
duplicate-heavy key-reuse traffic a response cache lives on — and
`replay:FILE` replays a JSONL trace (one request payload per line, in
order, cycling if the run outlasts the trace). The report carries
client-OBSERVED cache behaviour whenever the server stamps replies
with `X-Cache` (hits/misses/hit_rate and a hit-vs-miss latency split)
— measured at this end of the wire, not inferred from server stats.

Priority classes: `--mix interactive=0.8,batch=0.2` samples each
request's `priority` field from the given distribution (and the report
grows a per-class block: sent/ok/shed/quota-rejected, goodput, p50/p99
— the shed-not-collapse evidence per class). `--client-id` stamps every
request (the per-client quota twins), `--model` routes to one model of
a multi-model server.

Report: one JSON line — throughput, p50/p95/p99/mean/max latency, status
counts, rejection count, `retry_after_seen` (429/503 replies carrying a
Retry-After header — the back-off contract). Transport failures split
two ways: `conn_refused` (nothing listening — a killed/restarting
backend, never an executed request) vs `transport_errors` (reset,
timeout, everything else). `--retry-transport N` re-fires a request up
to N times after a transport failure or a 502 (inline in the same fire
thread, so the open-loop schedule is untouched) and counts each re-fire
in `transport_retries` — the fleet chaos twins assert "zero DROPPED"
(`transport_errors == 0` after bounded retries), not "zero transport
blips". `--smoke` is the CI entry:
closed-loop burst with tight defaults, nonzero exit unless every request
succeeded and the server's /stats and /healthz answer;
`--expect-models N` additionally requires the multi-model /stats block.

Examples:
    python tools/loadgen.py --url http://127.0.0.1:8000 \
        --requests 2000 --concurrency 16
    python tools/loadgen.py --url http://127.0.0.1:8000 \
        --mode open --rate 500 --duration 10 --shape spike \
        --mix interactive=0.7,batch=0.2,best_effort=0.1
    python tools/loadgen.py --smoke --url http://127.0.0.1:8000
"""

from __future__ import annotations

import argparse
import bisect
import json
import math
import random
import sys
import threading
import time
import urllib.error
import urllib.request


#: Priority-class vocabulary, mirrored from serve/control.py (this tool
#: stays jax/numpy-import-free on purpose; pinned equal by
#: tests/test_serve_control.py).
PRIORITY_CLASSES = ("interactive", "batch", "best_effort")


def parse_mix(spec):
    """``interactive=0.8,batch=0.2`` -> [(class, cumulative_weight)];
    None/empty = every request is the default class."""
    if not spec:
        return None
    weights = []
    for tok in spec.split(","):
        tok = tok.strip()
        if not tok:
            continue
        klass, sep, val = tok.partition("=")
        klass = klass.strip()
        if not sep or klass not in PRIORITY_CLASSES:
            raise SystemExit(
                f"--mix: expected CLASS=WEIGHT with CLASS one of "
                f"{list(PRIORITY_CLASSES)}, got {tok!r}")
        weights.append((klass, float(val)))
    total = sum(w for _, w in weights)
    if total <= 0:
        raise SystemExit(f"--mix {spec!r}: weights must sum > 0")
    cum, out = 0.0, []
    for klass, w in weights:
        cum += w / total
        out.append((klass, cum))
    return out


def pick_class(mix, rng) -> str:
    if not mix:
        return PRIORITY_CLASSES[0]
    r = rng.random()
    for klass, cum in mix:
        if r <= cum:
            return klass
    return mix[-1][0]


#: Rate-modulating shapes (the body round-robins); `zipf:S` /
#: `replay:FILE` are BODY shapes that ride a constant rate.
RATE_SHAPES = ("constant", "sine", "spike", "adversarial")


def parse_shape(spec: str):
    """Split ``--shape`` into (rate_shape, body_shape). ``zipf:S`` and
    ``replay:FILE`` pick bodies differently but fire at the constant
    rate; everything else modulates the rate with round-robin bodies.
    body_shape is None, ("zipf", S) or ("replay", path)."""
    if spec.startswith("zipf:"):
        try:
            s = float(spec.split(":", 1)[1])
        except ValueError:
            raise SystemExit(f"--shape {spec!r}: expected zipf:S with "
                             f"a numeric exponent S") from None
        if s < 0:
            raise SystemExit(f"--shape {spec!r}: exponent must be >= 0")
        return "constant", ("zipf", s)
    if spec.startswith("replay:"):
        path = spec.split(":", 1)[1]
        if not path:
            raise SystemExit("--shape replay: expected replay:FILE")
        return "constant", ("replay", path)
    if spec not in RATE_SHAPES:
        raise SystemExit(
            f"--shape {spec!r}: expected one of {list(RATE_SHAPES)}, "
            f"zipf:S, or replay:FILE")
    return spec, None


def zipf_cum(n: int, s: float):
    """Cumulative Zipf(s) weights over ranks 1..n — P(rank k) is
    proportional to 1/k^s, so rank 1 dominates at s >= 1 (the
    duplicate-heavy head a response cache feeds on) and s=0 degrades
    to uniform. Sampled by bisect on a uniform draw."""
    weights = [1.0 / (k + 1) ** s for k in range(n)]
    total = sum(weights)
    cum, out = 0.0, []
    for w in weights:
        cum += w / total
        out.append(cum)
    return out


def load_replay(path: str, extra_fields=None):
    """JSONL trace -> pre-serialized bodies, in trace order. Each line
    is one request payload (the dict POSTed to /predict); client-id /
    model stamps apply on top, same as generated bodies. The run cycles
    the trace when it outlasts it."""
    bodies = []
    with open(path, encoding="utf-8") as fh:
        for ln, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                payload = json.loads(line)
            except json.JSONDecodeError as exc:
                raise SystemExit(
                    f"--shape replay: {path}:{ln}: bad JSON ({exc})"
                ) from None
            if not isinstance(payload, dict):
                raise SystemExit(
                    f"--shape replay: {path}:{ln}: expected an object")
            payload.update(extra_fields or {})
            bodies.append(json.dumps(payload).encode())
    if not bodies:
        raise SystemExit(f"--shape replay: {path}: empty trace")
    return {None: bodies}


def rate_at(shape: str, base_rate: float, t: float, duration: float,
            spike_mult: float, rng_seed: int) -> float:
    """Instantaneous offered rate at time ``t`` for one traffic shape.
    Pure (the adversarial shape hashes the second index with the seed),
    so the schedule is unit-testable and reproducible."""
    if shape == "sine":
        # One diurnal period over the run: 0.2x at the trough, 1.8x at
        # the peak — the autoscaler sees both directions.
        return max(0.0, base_rate * (1.0 + 0.8 * math.sin(
            2.0 * math.pi * t / max(duration, 1e-9))))
    if shape == "spike":
        # Flat baseline, spike_mult burst through the middle fifth —
        # the scale-up trigger with a clean before/after.
        return base_rate * (spike_mult
                            if 0.4 <= t / max(duration, 1e-9) <= 0.6
                            else 1.0)
    if shape == "adversarial":
        # Per-second coin flip between near-silence and a 3x burst:
        # no trend to learn, maximal flap pressure on a controller.
        slot_rng = random.Random(rng_seed * 1000003 + int(t))
        return base_rate * (3.0 if slot_rng.random() < 0.5 else 0.1)
    return base_rate


def schedule(shape: str, rate: float, duration: float, seed: int,
             spike_mult: float = 5.0):
    """Fire times for one open-loop run: next-event stepping through
    the shape's instantaneous rate (1/rate(t) between events), so the
    offered load IS the shape, not a smoothed average of it."""
    times = []
    t = 0.0
    while t < duration:
        r = rate_at(shape, rate, t, duration, spike_mult, seed)
        if r <= 0:
            t += 0.05
            continue
        times.append(t)
        t += 1.0 / r
    return times


def _make_images(n_templates: int, images_per_request: int, seed: int,
                 extra_fields=None, mix=None):
    """Deterministic raw 28x28 uint8-valued images, pre-serialized to
    JSON bodies (serialization cost paid once, not per request). With a
    priority ``mix``, one body set per class (the class rides the
    body); ``extra_fields`` (model/client_id) stamp every body.
    Returns ``[(klass_or_None, body_bytes), ...]``."""
    rng = random.Random(seed)
    classes = [k for k, _ in mix] if mix else [None]
    bodies = {klass: [] for klass in classes}
    for _ in range(n_templates):
        imgs = [[[rng.randrange(256) for _ in range(28)] for _ in range(28)]
                for _ in range(images_per_request)]
        for klass in classes:
            payload = {"images": imgs}
            if klass is not None:
                payload["priority"] = klass
            payload.update(extra_fields or {})
            bodies[klass].append(json.dumps(payload).encode())
    return bodies


class Collector:
    """Thread-safe result accumulator (overall + per priority class)."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.latencies = []
        self.status = {}
        self.errors = 0
        self.conn_refused = 0
        self.transport_retries = 0
        self.not_launched = 0
        self.retry_after_seen = 0
        self.classes = {}
        # Client-OBSERVED cache behaviour: replies stamped `X-Cache:
        # hit|miss` by a caching server/router. Latencies split per
        # verdict so the report can show the hit-vs-compute gap as
        # measured at this end of the wire.
        self.cache_hits = 0
        self.cache_misses = 0
        self.hit_latencies = []
        self.miss_latencies = []

    def _class_rec(self, klass):
        rec = self.classes.get(klass)
        if rec is None:
            rec = self.classes[klass] = {
                "sent": 0, "status": {}, "latencies": []}
        return rec

    def record(self, status: int, latency_s: float, klass=None,
               retry_after: bool = False, cache=None) -> None:
        with self.lock:
            self.status[status] = self.status.get(status, 0) + 1
            if status == 200:
                self.latencies.append(latency_s)
                if cache == "hit":
                    self.cache_hits += 1
                    self.hit_latencies.append(latency_s)
                elif cache == "miss":
                    self.cache_misses += 1
                    self.miss_latencies.append(latency_s)
            if retry_after:
                self.retry_after_seen += 1
            if klass is not None:
                rec = self._class_rec(klass)
                rec["sent"] += 1
                rec["status"][status] = rec["status"].get(status, 0) + 1
                if status == 200:
                    rec["latencies"].append(latency_s)

    def record_error(self, refused: bool = False) -> None:
        """``refused=True`` = connection refused: nothing was listening,
        so the request was provably never executed — a different animal
        from a reset/timeout (which MAY have reached a handler). The
        fleet chaos twins assert on the two counters separately."""
        with self.lock:
            if refused:
                self.conn_refused += 1
            else:
                self.errors += 1

    def record_retry(self) -> None:
        with self.lock:
            self.transport_retries += 1

    def record_not_launched(self) -> None:
        """Open loop only: the schedule fired but the CLIENT could not
        launch (outstanding cap) — the client's limit, not a server
        drop, so it must not count as a transport error."""
        with self.lock:
            self.not_launched += 1


def _is_refused(exc) -> bool:
    """Connection refused, unwrapping urllib's URLError envelope."""
    if isinstance(exc, urllib.error.URLError):
        exc = exc.reason
    return isinstance(exc, ConnectionRefusedError)


def _one_request(url: str, body: bytes, timeout: float,
                 collector: Collector, klass=None,
                 retries: int = 0) -> None:
    """Fire one request; with ``retries`` > 0, transport failures and
    502s (the router's "backend failed" surface — the reply that says
    re-sending is a NEW request, not a double-dispatch) are re-fired
    inline in the same thread, so the open-loop schedule stays a
    schedule. Exactly one terminal outcome is recorded per call."""
    req = urllib.request.Request(
        url + "/predict", data=body,
        headers={"Content-Type": "application/json"})
    for attempt in range(retries + 1):
        t0 = time.perf_counter()
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                resp.read()
                collector.record(resp.status, time.perf_counter() - t0,
                                 klass=klass,
                                 cache=resp.headers.get("X-Cache"))
                return
        except urllib.error.HTTPError as exc:
            exc.read()
            if exc.code == 502 and attempt < retries:
                collector.record_retry()
                continue
            collector.record(
                exc.code, time.perf_counter() - t0, klass=klass,
                retry_after=exc.headers.get("Retry-After") is not None)
            return
        except Exception as exc:  # noqa: BLE001 - connection/timeout
            if attempt < retries:
                collector.record_retry()
                continue
            collector.record_error(refused=_is_refused(exc))
            return


def _pick_body(bodies, mix, rng, i, zipf=None):
    """``(klass, body)`` for request ``i``: class sampled from the mix,
    body round-robin within the class's template set — or, with
    ``zipf`` (cumulative weights from :func:`zipf_cum`), sampled
    per-request from the Zipf distribution over templates, which is
    what makes the traffic duplicate-heavy."""
    klass = pick_class(mix, rng) if mix else None
    per_class = bodies[klass]
    if zipf is not None:
        idx = min(bisect.bisect_left(zipf, rng.random()),
                  len(per_class) - 1)
        return klass, per_class[idx]
    return klass, per_class[i % len(per_class)]


def _salted(body: bytes, i: int) -> bytes:
    """Splice a per-request nonce field into a pre-serialized JSON body
    so every request is byte-unique. The DEFAULT drive salts: against a
    caching server, accidental duplicates from a small template pool
    would measure the cache, not the server — duplicate-heavy traffic
    is the explicit ``--shape zipf:S`` / ``replay:FILE`` opt-in. One
    slice copy per request; the server ignores unknown fields."""
    return body[:-1] + (',"nonce":%d}' % i).encode()


def run_closed(url: str, requests: int, concurrency: int, bodies,
               timeout: float, mix=None, seed: int = 0,
               retries: int = 0, zipf=None, salt: bool = False) -> Collector:
    collector = Collector()
    counter = {"next": 0}
    lock = threading.Lock()
    rng = random.Random(seed + 1)

    def worker(wid: int) -> None:
        while True:
            with lock:
                i = counter["next"]
                if i >= requests:
                    return
                counter["next"] = i + 1
                klass, body = _pick_body(bodies, mix, rng, i, zipf=zipf)
            if salt:
                body = _salted(body, i)
            _one_request(url, body, timeout, collector, klass=klass,
                         retries=retries)

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return collector


def run_open(url: str, rate: float, duration: float, bodies,
             timeout: float, max_outstanding: int = 512,
             shape: str = "constant", spike_mult: float = 5.0,
             mix=None, seed: int = 0, retries: int = 0,
             zipf=None, salt: bool = False) -> Collector:
    collector = Collector()
    sem = threading.Semaphore(max_outstanding)
    threads = []
    rng = random.Random(seed + 1)
    fire_times = schedule(shape, rate, duration, seed,
                          spike_mult=spike_mult)
    t_start = time.perf_counter()
    for i, t_fire in enumerate(fire_times):
        now = time.perf_counter()
        t_next = t_start + t_fire
        if t_next > now:
            time.sleep(t_next - now)
        if not sem.acquire(blocking=False):
            # The schedule never waits for the server (that would be
            # closed-loop in disguise); a send the client can't launch
            # is counted (never silently skipped) — as not_launched,
            # distinct from transport errors: it is the CLIENT's
            # outstanding cap, not a dropped request.
            collector.record_not_launched()
            continue
        klass, body = _pick_body(bodies, mix, rng, i, zipf=zipf)
        if salt:
            body = _salted(body, i)

        def fire(body=body, klass=klass):
            try:
                _one_request(url, body, timeout, collector, klass=klass,
                             retries=retries)
            finally:
                sem.release()

        th = threading.Thread(target=fire, daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout)
    return collector


def _percentile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def report(collector: Collector, wall_s: float, mode: str) -> dict:
    lats = sorted(collector.latencies)
    ms = lambda s: round(s * 1e3, 3)  # noqa: E731
    ok = collector.status.get(200, 0)
    out = {
        "mode": mode,
        "wall_s": round(wall_s, 3),
        "ok": ok,
        "rejected": collector.status.get(503, 0),
        "quota_rejected": collector.status.get(429, 0),
        "retry_after_seen": collector.retry_after_seen,
        "status_counts": {str(k): v
                          for k, v in sorted(collector.status.items())},
        "transport_errors": collector.errors,
        "conn_refused": collector.conn_refused,
        "transport_retries": collector.transport_retries,
        "not_launched": collector.not_launched,
        "throughput_rps": round(ok / wall_s, 2) if wall_s > 0 else 0.0,
        "latency_ms": {
            "p50": ms(_percentile(lats, 0.50)),
            "p95": ms(_percentile(lats, 0.95)),
            "p99": ms(_percentile(lats, 0.99)),
            "mean": ms(sum(lats) / len(lats)) if lats else 0.0,
            "max": ms(lats[-1]) if lats else 0.0,
        },
    }
    if collector.cache_hits or collector.cache_misses:
        # Client-observed cache verdicts (X-Cache reply headers) —
        # measured hit rate and the hit-vs-compute latency gap as the
        # CLIENT saw them, independent of the server's own counters.
        hit_lats = sorted(collector.hit_latencies)
        miss_lats = sorted(collector.miss_latencies)
        seen = collector.cache_hits + collector.cache_misses
        out["cache_client"] = {
            "hits": collector.cache_hits,
            "misses": collector.cache_misses,
            "hit_rate": round(collector.cache_hits / seen, 4),
            "hit_latency_ms": {
                "p50": ms(_percentile(hit_lats, 0.50)),
                "p99": ms(_percentile(hit_lats, 0.99)),
            },
            "miss_latency_ms": {
                "p50": ms(_percentile(miss_lats, 0.50)),
                "p99": ms(_percentile(miss_lats, 0.99)),
            },
        }
    if collector.classes:
        # Per-priority-class goodput + tail: the shed-not-collapse
        # evidence per class (interactive p99 should stay BELOW batch
        # p99 under overload, and best_effort should shed first).
        out["classes"] = {}
        for klass, rec in sorted(collector.classes.items()):
            clats = sorted(rec["latencies"])
            cok = rec["status"].get(200, 0)
            out["classes"][klass] = {
                "sent": rec["sent"],
                "ok": cok,
                "shed": rec["status"].get(503, 0),
                "quota_rejected": rec["status"].get(429, 0),
                "goodput_rps": round(cok / wall_s, 2)
                if wall_s > 0 else 0.0,
                "latency_ms": {
                    "p50": ms(_percentile(clats, 0.50)),
                    "p99": ms(_percentile(clats, 0.99)),
                },
            }
    return out


def _get_json(url: str, path: str, timeout: float) -> dict:
    with urllib.request.urlopen(url + path, timeout=timeout) as resp:
        return json.loads(resp.read())


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--url", type=str, default="http://127.0.0.1:8000")
    p.add_argument("--mode", type=str, default="closed",
                   choices=["closed", "open"])
    p.add_argument("--requests", type=int, default=1000,
                   help="closed loop: total requests")
    p.add_argument("--concurrency", type=int, default=8,
                   help="closed loop: workers with one request in flight "
                        "each")
    p.add_argument("--rate", type=float, default=200.0,
                   help="open loop: target requests/sec (the BASE rate "
                        "the shape modulates)")
    p.add_argument("--duration", type=float, default=5.0,
                   help="open loop: seconds to run")
    p.add_argument("--shape", type=str, default="constant",
                   help="traffic shape: 'sine' = one diurnal period "
                        "over the duration (0.2x..1.8x), 'spike' = "
                        "--spike-mult x burst through the middle "
                        "fifth, 'adversarial' = seeded per-second "
                        "flips between 0.1x and 3x (no trend for a "
                        "controller to learn). Two BODY shapes ride a "
                        "constant rate and work in closed mode too: "
                        "'zipf:S' samples each request's template from "
                        "Zipf(S) — duplicate-heavy key reuse, the "
                        "response-cache workload — and 'replay:FILE' "
                        "replays a JSONL trace (one request payload "
                        "per line, in order, cycling)")
    p.add_argument("--spike-mult", type=float, default=5.0,
                   help="spike shape: burst multiple of --rate")
    p.add_argument("--mix", type=str, default=None,
                   metavar="CLASS=W[,CLASS=W...]",
                   help="priority-class request mix (e.g. "
                        "interactive=0.8,batch=0.2): each request's "
                        "'priority' field is sampled from this "
                        "distribution and the report gains a per-class "
                        "goodput/p99 block")
    p.add_argument("--client-id", type=str, default=None,
                   help="stamp every request with this client_id (the "
                        "per-client quota plane); omit for anonymous")
    p.add_argument("--model", type=str, default=None,
                   help="stamp every request with this model field "
                        "(multi-model servers route on it; required "
                        "there)")
    p.add_argument("--images-per-request", type=int, default=1)
    p.add_argument("--timeout", type=float, default=30.0)
    p.add_argument("--retry-transport", type=int, default=0,
                   metavar="N",
                   help="re-fire a request up to N times after a "
                        "transport failure or a 502 (bounded, inline in "
                        "the same fire thread so the open-loop schedule "
                        "is preserved); each re-fire counts in "
                        "transport_retries — lets fleet chaos twins "
                        "assert zero DROPPED requests rather than zero "
                        "transport blips")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--smoke", action="store_true",
                   help="CI gate: closed-loop burst; exit nonzero unless "
                        "every request succeeded and /healthz + /stats "
                        "answer")
    p.add_argument("--expect-replicas", type=int, default=0,
                   help="smoke: additionally require /stats to report "
                        "exactly this many engine replicas (the pooled "
                        "--serve-devices data plane) whose batch counts "
                        "sum to the server's batch total; 0 skips the "
                        "check")
    p.add_argument("--expect-precision", type=str, default=None,
                   help="smoke: additionally require /stats to report "
                        "this serve_precision (e.g. 'bf16' — the "
                        "quantized --serve-precision plane; the report "
                        "always carries serve_precision, and the "
                        "canary block when a shadow canary is active)")
    p.add_argument("--expect-fused", action="store_true",
                   help="smoke: additionally require /stats to report "
                        "fused=true (the whole-program serving plane — "
                        "raw bytes to logits in one XLA program; the "
                        "server's default unless started --no-fuse)")
    p.add_argument("--expect-mode", type=str, default=None,
                   help="smoke: additionally require /stats to report "
                        "this serve_mode (e.g. 'tensor' — the sharded "
                        "--serve-mode data plane), with the mesh-shape "
                        "fields present for sharded modes")
    p.add_argument("--expect-stages", type=int, default=0,
                   help="smoke: additionally require /stats to report "
                        "this many pipeline stages per chain "
                        "(pipeline_stages — the --serve-mode pipeline "
                        "MPMD plane; mirrors --expect-groups); the "
                        "report always carries pipeline_stages when the "
                        "server serves a staged mode; 0 skips the check")
    p.add_argument("--expect-models", type=int, default=0,
                   help="smoke: additionally require /stats to carry a "
                        "multi-model `models` block with exactly this "
                        "many planes (the --model-set server); 0 skips "
                        "the check")
    p.add_argument("--expect-groups", type=int, default=0,
                   help="smoke: additionally require /stats to report "
                        "exactly this many ACTIVE (non-quarantined) "
                        "dispatch groups — the post-regroup/post-resize "
                        "topology assertion (mirrors --expect-replicas/"
                        "--expect-mode; the report always carries "
                        "topology_generation when the server exposes "
                        "it); 0 skips the check")
    args = p.parse_args(argv)

    url = args.url.rstrip("/")
    mix = parse_mix(args.mix)
    rate_shape, body_shape = parse_shape(args.shape)
    extra_fields = {}
    if args.client_id:
        extra_fields["client_id"] = args.client_id
    if args.model:
        extra_fields["model"] = args.model
    zipf = None
    if body_shape and body_shape[0] == "replay":
        # Trace bodies carry their own priority fields; --mix would
        # fight the trace, so it is rejected rather than ignored.
        if mix:
            raise SystemExit("--shape replay:FILE and --mix are "
                             "mutually exclusive (the trace IS the mix)")
        bodies = load_replay(body_shape[1], extra_fields)
    else:
        bodies = _make_images(
            n_templates=min(16, max(1, args.requests)),
            images_per_request=args.images_per_request, seed=args.seed,
            extra_fields=extra_fields, mix=mix)
        if body_shape and body_shape[0] == "zipf":
            zipf = zipf_cum(len(bodies[next(iter(bodies))]),
                            body_shape[1])

    # Byte-unique bodies by DEFAULT: only the duplicate-opt-in shapes
    # (zipf, replay) send repeated bytes, so a caching server's compute
    # path is what the default drive measures.
    salt = body_shape is None
    t0 = time.perf_counter()
    if args.mode == "open" and not args.smoke:
        collector = run_open(url, args.rate, args.duration, bodies,
                             args.timeout, shape=rate_shape,
                             spike_mult=args.spike_mult, mix=mix,
                             seed=args.seed,
                             retries=args.retry_transport, zipf=zipf,
                             salt=salt)
    else:
        collector = run_closed(url, args.requests, args.concurrency,
                               bodies, args.timeout, mix=mix,
                               seed=args.seed,
                               retries=args.retry_transport, zipf=zipf,
                               salt=salt)
    out = report(collector, time.perf_counter() - t0,
                 "closed" if args.smoke else args.mode)
    out["shape"] = args.shape
    # Data-plane shape from /stats on EVERY run (not just smoke): a
    # loadgen report without the serve mode and mesh shape can't say
    # WHAT it measured. Smoke mode reuses its own /stats fetch below
    # (one snapshot feeds both the assertions and these fields);
    # otherwise best-effort — a server predating the fields (or an
    # unreachable /stats) just omits them.
    def _shape_fields(stats: dict) -> None:
        for key in ("serve_mode", "serve_precision", "fused", "canary",
                    "serve_devices", "mesh_devices",
                    "mesh_groups", "pipeline_stages", "max_inflight",
                    "topology_generation", "groups", "active_groups",
                    "quarantined_groups", "slice_straddling_groups",
                    "model_set", "quota", "autoscaler"):
            if key in stats:
                out[key] = stats[key]

    if not args.smoke:
        try:
            _shape_fields(_get_json(url, "/stats", args.timeout))
        except Exception:  # noqa: BLE001 - shape fields are advisory
            pass

    rc = 0
    if args.smoke:
        # The smoke bar: every request answered 200, and the health/stats
        # surface is live and carries the latency quantiles + batch
        # histogram the acceptance criteria name.
        try:
            health = _get_json(url, "/healthz", args.timeout)
            stats = _get_json(url, "/stats", args.timeout)
            _shape_fields(stats)
            out["healthz"] = health
            out["stats_keys"] = sorted(stats)
            # On a multi-model server the top-level block is the
            # DEFAULT plane's; a smoke driving --model must judge the
            # latency/histogram surface of the plane it actually hit.
            plane = stats
            if args.model and isinstance(stats.get("models"), dict):
                plane = stats["models"].get(args.model) or {}
            smoke_ok = (
                health.get("ok") is True
                and out["ok"] == args.requests
                and out["transport_errors"] == 0
                and out["conn_refused"] == 0
                and "p50" in plane.get("latency_ms", {})
                and "p99" in plane.get("latency_ms", {})
                and plane.get("batch_histogram")
            )
            if args.expect_replicas:
                # The pooled data plane really is pooled: one /stats row
                # per replica, and every executed batch attributed to
                # one of them. (No per-replica minimum: the least-loaded
                # dispatcher legitimately concentrates an underloaded
                # burst on few replicas.)
                replicas = stats.get("replicas") or {}
                out["replicas"] = replicas
                smoke_ok = (
                    smoke_ok
                    and len(replicas) == args.expect_replicas
                    and sum(r.get("batches", 0) for r in replicas.values())
                    == stats.get("batches")
                )
            if args.expect_precision:
                # The quantized plane really is the requested one:
                # /stats names the serving precision (always present on
                # precision-aware servers).
                smoke_ok = (
                    smoke_ok
                    and stats.get("serve_precision")
                    == args.expect_precision
                )
            if args.expect_fused:
                # The whole-program plane really is live: /stats says
                # raw requests ride the fused bucket programs.
                smoke_ok = smoke_ok and stats.get("fused") is True
            if args.expect_mode:
                # The sharded data plane really is the requested one:
                # /stats names the mode, and sharded modes carry their
                # mesh shape (mesh_devices x mesh_groups).
                smoke_ok = (
                    smoke_ok
                    and stats.get("serve_mode") == args.expect_mode
                    and (args.expect_mode == "replicated"
                         or (stats.get("mesh_devices", 0) >= 1
                             and stats.get("mesh_groups", 0) >= 1))
                )
            if args.expect_stages:
                # The MPMD plane really is staged: /stats says how many
                # per-chip stage programs each chain runs.
                smoke_ok = (
                    smoke_ok
                    and stats.get("pipeline_stages") == args.expect_stages
                )
            if args.expect_models:
                # The multi-model server really serves N planes: /stats
                # carries one `models` entry per plane, each with its
                # own latency/reload schema.
                models = stats.get("models") or {}
                out["models_served"] = sorted(models)
                smoke_ok = (
                    smoke_ok
                    and len(models) == args.expect_models
                    and all("latency_ms" in m for m in models.values())
                )
            if args.expect_groups:
                # The post-regroup/post-resize topology really landed:
                # exactly N dispatch groups are active (quarantined ones
                # excluded — a group mid-rebuild is not serving
                # capacity), per the pool's own topology block.
                smoke_ok = (
                    smoke_ok
                    and stats.get("active_groups") == args.expect_groups
                )
        except Exception as exc:  # noqa: BLE001
            out["smoke_error"] = repr(exc)
            smoke_ok = False
        out["smoke_ok"] = bool(smoke_ok)
        rc = 0 if smoke_ok else 1
    print(json.dumps(out))
    return rc


if __name__ == "__main__":
    sys.exit(main())
