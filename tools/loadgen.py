#!/usr/bin/env python3
"""Load generator for the serving endpoint (`tpu-mnist serve`).

Pure stdlib on purpose — no jax, no numpy — so it starts in milliseconds,
runs from any box that can reach the server, and measures the SERVER, not
its own import time. Two disciplines:

- **closed loop** (default): C workers each keep exactly one request in
  flight, back to back — measures throughput at a fixed concurrency and
  the latency that concurrency buys.
- **open loop**: requests fire on a fixed-rate schedule regardless of
  completions — the honest tail-latency discipline (closed-loop
  coordinated omission hides queueing collapse: a slow server slows the
  CLIENTS down). Overload shows up as 503 rejections and p99 growth
  instead of a silently reduced send rate.

Report: one JSON line — throughput, p50/p95/p99/mean/max latency, status
counts, rejection count. `--smoke` is the CI entry: closed-loop burst
with tight defaults, nonzero exit unless every request succeeded and the
server's /stats and /healthz answer.

Examples:
    python tools/loadgen.py --url http://127.0.0.1:8000 \
        --requests 2000 --concurrency 16
    python tools/loadgen.py --url http://127.0.0.1:8000 \
        --mode open --rate 500 --duration 10
    python tools/loadgen.py --smoke --url http://127.0.0.1:8000
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import threading
import time
import urllib.error
import urllib.request


def _make_images(n_templates: int, images_per_request: int, seed: int):
    """Deterministic raw 28x28 uint8-valued images as nested lists,
    pre-serialized to JSON bodies (serialization cost paid once, not per
    request)."""
    rng = random.Random(seed)
    bodies = []
    for _ in range(n_templates):
        imgs = [[[rng.randrange(256) for _ in range(28)] for _ in range(28)]
                for _ in range(images_per_request)]
        bodies.append(json.dumps({"images": imgs}).encode())
    return bodies


class Collector:
    """Thread-safe result accumulator."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.latencies = []
        self.status = {}
        self.errors = 0

    def record(self, status: int, latency_s: float) -> None:
        with self.lock:
            self.status[status] = self.status.get(status, 0) + 1
            if status == 200:
                self.latencies.append(latency_s)

    def record_error(self) -> None:
        with self.lock:
            self.errors += 1


def _one_request(url: str, body: bytes, timeout: float,
                 collector: Collector) -> None:
    req = urllib.request.Request(
        url + "/predict", data=body,
        headers={"Content-Type": "application/json"})
    t0 = time.perf_counter()
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            resp.read()
            collector.record(resp.status, time.perf_counter() - t0)
    except urllib.error.HTTPError as exc:
        exc.read()
        collector.record(exc.code, time.perf_counter() - t0)
    except Exception:  # noqa: BLE001 - connection/timeout errors
        collector.record_error()


def run_closed(url: str, requests: int, concurrency: int, bodies,
               timeout: float) -> Collector:
    collector = Collector()
    counter = {"next": 0}
    lock = threading.Lock()

    def worker(wid: int) -> None:
        while True:
            with lock:
                i = counter["next"]
                if i >= requests:
                    return
                counter["next"] = i + 1
            _one_request(url, bodies[i % len(bodies)], timeout, collector)

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return collector


def run_open(url: str, rate: float, duration: float, bodies,
             timeout: float, max_outstanding: int = 512) -> Collector:
    collector = Collector()
    sem = threading.Semaphore(max_outstanding)
    threads = []
    interval = 1.0 / max(rate, 1e-9)
    t_start = time.perf_counter()
    i = 0
    while True:
        t_next = t_start + i * interval
        now = time.perf_counter()
        if t_next - t_start >= duration:
            break
        if t_next > now:
            time.sleep(t_next - now)
        if not sem.acquire(blocking=False):
            # The schedule never waits for the server (that would be
            # closed-loop in disguise); a send the client can't launch is
            # counted as an error, not silently skipped.
            collector.record_error()
            i += 1
            continue

        def fire(body=bodies[i % len(bodies)]):
            try:
                _one_request(url, body, timeout, collector)
            finally:
                sem.release()

        th = threading.Thread(target=fire, daemon=True)
        th.start()
        threads.append(th)
        i += 1
    for th in threads:
        th.join(timeout)
    return collector


def _percentile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def report(collector: Collector, wall_s: float, mode: str) -> dict:
    lats = sorted(collector.latencies)
    ms = lambda s: round(s * 1e3, 3)  # noqa: E731
    ok = collector.status.get(200, 0)
    return {
        "mode": mode,
        "wall_s": round(wall_s, 3),
        "ok": ok,
        "rejected": collector.status.get(503, 0),
        "status_counts": {str(k): v
                          for k, v in sorted(collector.status.items())},
        "transport_errors": collector.errors,
        "throughput_rps": round(ok / wall_s, 2) if wall_s > 0 else 0.0,
        "latency_ms": {
            "p50": ms(_percentile(lats, 0.50)),
            "p95": ms(_percentile(lats, 0.95)),
            "p99": ms(_percentile(lats, 0.99)),
            "mean": ms(sum(lats) / len(lats)) if lats else 0.0,
            "max": ms(lats[-1]) if lats else 0.0,
        },
    }


def _get_json(url: str, path: str, timeout: float) -> dict:
    with urllib.request.urlopen(url + path, timeout=timeout) as resp:
        return json.loads(resp.read())


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--url", type=str, default="http://127.0.0.1:8000")
    p.add_argument("--mode", type=str, default="closed",
                   choices=["closed", "open"])
    p.add_argument("--requests", type=int, default=1000,
                   help="closed loop: total requests")
    p.add_argument("--concurrency", type=int, default=8,
                   help="closed loop: workers with one request in flight "
                        "each")
    p.add_argument("--rate", type=float, default=200.0,
                   help="open loop: target requests/sec")
    p.add_argument("--duration", type=float, default=5.0,
                   help="open loop: seconds to run")
    p.add_argument("--images-per-request", type=int, default=1)
    p.add_argument("--timeout", type=float, default=30.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--smoke", action="store_true",
                   help="CI gate: closed-loop burst; exit nonzero unless "
                        "every request succeeded and /healthz + /stats "
                        "answer")
    p.add_argument("--expect-replicas", type=int, default=0,
                   help="smoke: additionally require /stats to report "
                        "exactly this many engine replicas (the pooled "
                        "--serve-devices data plane) whose batch counts "
                        "sum to the server's batch total; 0 skips the "
                        "check")
    p.add_argument("--expect-precision", type=str, default=None,
                   help="smoke: additionally require /stats to report "
                        "this serve_precision (e.g. 'bf16' — the "
                        "quantized --serve-precision plane; the report "
                        "always carries serve_precision, and the "
                        "canary block when a shadow canary is active)")
    p.add_argument("--expect-mode", type=str, default=None,
                   help="smoke: additionally require /stats to report "
                        "this serve_mode (e.g. 'tensor' — the sharded "
                        "--serve-mode data plane), with the mesh-shape "
                        "fields present for sharded modes")
    p.add_argument("--expect-stages", type=int, default=0,
                   help="smoke: additionally require /stats to report "
                        "this many pipeline stages per chain "
                        "(pipeline_stages — the --serve-mode pipeline "
                        "MPMD plane; mirrors --expect-groups); the "
                        "report always carries pipeline_stages when the "
                        "server serves a staged mode; 0 skips the check")
    p.add_argument("--expect-groups", type=int, default=0,
                   help="smoke: additionally require /stats to report "
                        "exactly this many ACTIVE (non-quarantined) "
                        "dispatch groups — the post-regroup/post-resize "
                        "topology assertion (mirrors --expect-replicas/"
                        "--expect-mode; the report always carries "
                        "topology_generation when the server exposes "
                        "it); 0 skips the check")
    args = p.parse_args(argv)

    url = args.url.rstrip("/")
    bodies = _make_images(
        n_templates=min(16, max(1, args.requests)),
        images_per_request=args.images_per_request, seed=args.seed)

    t0 = time.perf_counter()
    if args.mode == "open" and not args.smoke:
        collector = run_open(url, args.rate, args.duration, bodies,
                             args.timeout)
    else:
        collector = run_closed(url, args.requests, args.concurrency,
                               bodies, args.timeout)
    out = report(collector, time.perf_counter() - t0,
                 "closed" if args.smoke else args.mode)
    # Data-plane shape from /stats on EVERY run (not just smoke): a
    # loadgen report without the serve mode and mesh shape can't say
    # WHAT it measured. Smoke mode reuses its own /stats fetch below
    # (one snapshot feeds both the assertions and these fields);
    # otherwise best-effort — a server predating the fields (or an
    # unreachable /stats) just omits them.
    def _shape_fields(stats: dict) -> None:
        for key in ("serve_mode", "serve_precision", "canary",
                    "serve_devices", "mesh_devices",
                    "mesh_groups", "pipeline_stages", "max_inflight",
                    "topology_generation", "groups", "active_groups",
                    "quarantined_groups", "slice_straddling_groups"):
            if key in stats:
                out[key] = stats[key]

    if not args.smoke:
        try:
            _shape_fields(_get_json(url, "/stats", args.timeout))
        except Exception:  # noqa: BLE001 - shape fields are advisory
            pass

    rc = 0
    if args.smoke:
        # The smoke bar: every request answered 200, and the health/stats
        # surface is live and carries the latency quantiles + batch
        # histogram the acceptance criteria name.
        try:
            health = _get_json(url, "/healthz", args.timeout)
            stats = _get_json(url, "/stats", args.timeout)
            _shape_fields(stats)
            out["healthz"] = health
            out["stats_keys"] = sorted(stats)
            smoke_ok = (
                health.get("ok") is True
                and out["ok"] == args.requests
                and out["transport_errors"] == 0
                and "p50" in stats.get("latency_ms", {})
                and "p99" in stats.get("latency_ms", {})
                and stats.get("batch_histogram")
            )
            if args.expect_replicas:
                # The pooled data plane really is pooled: one /stats row
                # per replica, and every executed batch attributed to
                # one of them. (No per-replica minimum: the least-loaded
                # dispatcher legitimately concentrates an underloaded
                # burst on few replicas.)
                replicas = stats.get("replicas") or {}
                out["replicas"] = replicas
                smoke_ok = (
                    smoke_ok
                    and len(replicas) == args.expect_replicas
                    and sum(r.get("batches", 0) for r in replicas.values())
                    == stats.get("batches")
                )
            if args.expect_precision:
                # The quantized plane really is the requested one:
                # /stats names the serving precision (always present on
                # precision-aware servers).
                smoke_ok = (
                    smoke_ok
                    and stats.get("serve_precision")
                    == args.expect_precision
                )
            if args.expect_mode:
                # The sharded data plane really is the requested one:
                # /stats names the mode, and sharded modes carry their
                # mesh shape (mesh_devices x mesh_groups).
                smoke_ok = (
                    smoke_ok
                    and stats.get("serve_mode") == args.expect_mode
                    and (args.expect_mode == "replicated"
                         or (stats.get("mesh_devices", 0) >= 1
                             and stats.get("mesh_groups", 0) >= 1))
                )
            if args.expect_stages:
                # The MPMD plane really is staged: /stats says how many
                # per-chip stage programs each chain runs.
                smoke_ok = (
                    smoke_ok
                    and stats.get("pipeline_stages") == args.expect_stages
                )
            if args.expect_groups:
                # The post-regroup/post-resize topology really landed:
                # exactly N dispatch groups are active (quarantined ones
                # excluded — a group mid-rebuild is not serving
                # capacity), per the pool's own topology block.
                smoke_ok = (
                    smoke_ok
                    and stats.get("active_groups") == args.expect_groups
                )
        except Exception as exc:  # noqa: BLE001
            out["smoke_error"] = repr(exc)
            smoke_ok = False
        out["smoke_ok"] = bool(smoke_ok)
        rc = 0 if smoke_ok else 1
    print(json.dumps(out))
    return rc


if __name__ == "__main__":
    sys.exit(main())
