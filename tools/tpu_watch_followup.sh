#!/bin/bash
# Round-3 follow-up capture: the main watcher (tools/tpu_watch.sh) already
# landed bench.json + northstar.json + kernels.json in the 03:48Z recovery
# window; the link wedged again before (a) the tests_tpu suite could re-run
# with the session's test fixes and (b) a warm-compile-cache north-star
# could demonstrate the steady-state (sub-60s) figure. Poll for the next
# recovery and capture exactly those two, then exit. Safe to re-run.
set -u
OUT=/root/repo/tools/captured
mkdir -p "$OUT"
export BENCH_COMPILE_CACHE=/root/repo/.xla_cache
while true; do
  if timeout 90 python -c "import jax; assert jax.default_backend()=='tpu'; import jax.numpy as jnp; float(jnp.sum(jnp.ones((8,8))))" >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) TPU alive - followup capturing" >> "$OUT/watch.log"
    # Wait out any hermetic-suite run: this host has ONE core, and a
    # concurrent pytest would pollute the wall-clock measurements below.
    for _ in $(seq 1 60); do
      pgrep -f "pytest /root/repo/tests/" >/dev/null 2>&1 || \
        pgrep -f "pytest tests/" >/dev/null 2>&1 || break
      sleep 30
    done
    # Separate file: tests_tpu.log is the 03:48Z capture BASELINE.md
    # cites (6/9 pre-fix); the re-run must not overwrite that evidence.
    timeout 1800 python -m pytest /root/repo/tests_tpu/ -q \
      > "$OUT/tests_tpu_rerun.log" 2>&1
    TT_RC=$?
    echo "$(date -u +%FT%TZ) followup tests_tpu rc=$TT_RC (tests_tpu_rerun.log)" >> "$OUT/watch.log"
    # Warm-cache north star: same config as the cold capture; the compile
    # cache persisted from the 03:48Z run, so this measures the wall-clock
    # a user's SECOND run experiences (the cold figure stays in
    # northstar.json — the two are labelled, never conflated).
    timeout 1800 python /root/repo/tools/northstar.py \
      --dataset synthetic --epochs 20 --batch-size 512 --target 0.99 \
      --compile-cache "$BENCH_COMPILE_CACHE" \
      --root /tmp/ns_tpu_warm > "$OUT/northstar_warm.json.new" 2>> "$OUT/watch.log"
    NS_RC=$?
    if [ "$NS_RC" -eq 0 ]; then
      mv "$OUT/northstar_warm.json.new" "$OUT/northstar_warm.json"
    else
      cat "$OUT/northstar_warm.json.new" >> "$OUT/watch.log" 2>/dev/null
      rm -f "$OUT/northstar_warm.json.new"
    fi
    # Flash block-size sweep (fwd+bwd, T in {1k,2k,4k} x block in
    # {128,256,512} vs dense): the data that turns _block_sizes's
    # length-dependent heuristic into a measured choice.
    timeout 1800 python /root/repo/tools/sweep_flash.py \
      > "$OUT/flash_sweep.json.new" 2>> "$OUT/watch.log"
    FS_RC=$?
    if [ "$FS_RC" -eq 0 ]; then
      mv "$OUT/flash_sweep.json.new" "$OUT/flash_sweep.json"
    else
      cat "$OUT/flash_sweep.json.new" >> "$OUT/watch.log" 2>/dev/null
      rm -f "$OUT/flash_sweep.json.new"
    fi
    echo "$(date -u +%FT%TZ) followup done tests_tpu_rc=$TT_RC northstar_warm_rc=$NS_RC flash_sweep_rc=$FS_RC" >> "$OUT/watch.log"
    git -C /root/repo add tools/captured \
      && git -C /root/repo commit -q \
        -m "tools/captured: followup capture tests_tpu rc=$TT_RC, warm northstar rc=$NS_RC, flash sweep rc=$FS_RC" \
        -- tools/captured >> "$OUT/watch.log" 2>&1
    if [ "$TT_RC" -ne 0 ] || [ "$NS_RC" -ne 0 ] || [ "$FS_RC" -ne 0 ]; then
      echo "$(date -u +%FT%TZ) followup INCOMPLETE - will retry" >> "$OUT/watch.log"
      sleep 300
      continue
    fi
    exit 0
  fi
  echo "$(date -u +%FT%TZ) tpu still down (followup)" >> "$OUT/watch.log"
  sleep 300
done
