"""North-star measurement: wall-clock and epochs to >=99% MNIST test acc.

BASELINE.md's targets (from BASELINE.json north_star) are >=99% test
accuracy in <60 s wall-clock on TPU, measured on the CNN (the reference's
own Linear(784,10) ceilings at ~92-93%,
``/root/reference/multi_proc_single_gpu.py:119-126``). The reference
publishes no numbers of its own (README.md:1-62), so this runner produces
the only measured row.

Prints one JSON line:
  {"target_acc": 0.99, "reached": bool, "epochs_to_target": N,
   "seconds_to_target": S, "seconds_total": S, "best_acc": A,
   "backend": ..., "dataset": ..., "epoch_log": [...]}

Wall-clock starts BEFORE model/loader construction and includes compile
time — the honest end-to-end number a user experiences. Per-epoch entries
carry cumulative seconds so the compile-vs-train split is visible.

Usage:  python tools/northstar.py [--epochs 20] [--batch-size 512]
        [--dataset mnist|synthetic] [--target 0.99] [--lr 1e-3]
Real MNIST is used when the IDX files are in --root (or --download pulls
them); otherwise the synthetic generator stands in, and the JSON labels
the dataset honestly so the two are never conflated.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=512)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--target", type=float, default=0.99)
    p.add_argument("--dataset", type=str, default="mnist",
                   choices=["mnist", "synthetic"])
    p.add_argument("--root", type=str, default="data")
    p.add_argument("--download", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--synthetic-train-size", type=int, default=60000)
    p.add_argument("--synthetic-test-size", type=int, default=10000)
    p.add_argument("--compile-cache", type=str, default=None,
                   help="persistent XLA compile cache dir (forwarded to "
                        "the CLI): a repeat measurement skips the compile "
                        "seconds that dominate short runs")
    p.add_argument("--epoch-gather", type=str, default="host",
                   choices=["host", "device"],
                   help="input path for the measured run. Default host: "
                        "the measured winner on chip (375,868 vs 337,085 "
                        "img/s/chip for device-gather, "
                        "tools/captured/bench.json round 3 — flipped in "
                        "round 5 per VERDICT #4 after two rounds of "
                        "deferral). device keeps the dataset resident in "
                        "HBM with ~KB/epoch host traffic: the documented "
                        "memory/host-bandwidth saver, selectable here so "
                        "the next chip window can still measure it.")
    args = p.parse_args()

    t0 = time.perf_counter()

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # The axon plugin force-writes jax_platforms on import; honor an
        # explicit CPU request (smoke tests) the way tests/conftest.py does.
        jax.config.update("jax_platforms", "cpu")

    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    cli_args = [
        "--dataset", args.dataset, "--model", "cnn",
        "--epochs", str(args.epochs), "--batch-size", str(args.batch_size),
        "--lr", str(args.lr), "--seed", str(args.seed),
        "--root", args.root,
        "--checkpoint-dir", os.path.join(args.root, "northstar_ckpt"),
        "--synthetic-train-size", str(args.synthetic_train_size),
        "--synthetic-test-size", str(args.synthetic_test_size),
        # Trajectory-identical either way (tests/test_device_gather.py);
        # the default is the measured-faster host path, see the flag help.
        "--epoch-gather", args.epoch_gather,
        # This runner labels the dataset in its own output (the
        # "synthetic (mnist files unavailable)" relabel below), so the
        # fallback is safe here where the bare CLI now fails fast.
        "--allow-synthetic",
    ]
    if args.download:
        cli_args.append("--download")
    if args.compile_cache:
        cli_args += ["--compile-cache", args.compile_cache]
    ns = build_parser().parse_args(cli_args)

    epoch_log = []
    reached_epoch = None
    reached_s = None

    def on_epoch(epoch: int, history_row: dict) -> bool:
        nonlocal reached_epoch, reached_s
        now = time.perf_counter() - t0
        row = {"epoch": epoch, "seconds": round(now, 2),
               "test_acc": round(history_row["test_acc"], 5),
               "train_loss": round(history_row["train_loss"], 6)}
        epoch_log.append(row)
        print(f"northstar: epoch {epoch} t={now:.1f}s "
              f"acc={history_row['test_acc'] * 100:.2f}%", flush=True)
        if reached_epoch is None and history_row["test_acc"] >= args.target:
            reached_epoch = epoch
            reached_s = now
            return True  # stop: target hit
        return False

    summary = run(ns, epoch_callback=on_epoch)
    total = time.perf_counter() - t0

    dataset = args.dataset
    if dataset == "mnist" and summary.get("dataset_synthesized"):
        dataset = "synthetic (mnist files unavailable)"

    from pytorch_distributed_mnist_tpu.utils.compile_cache import (
        active_cache_dir,
    )

    out = {
        "target_acc": args.target,
        "reached": reached_epoch is not None,
        "epochs_to_target": (reached_epoch + 1) if reached_epoch is not None
        else None,
        "seconds_to_target": round(reached_s, 2) if reached_s else None,
        "seconds_total": round(total, 2),
        "best_acc": round(summary["best_acc"], 5),
        "backend": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
        "n_chips": jax.device_count(),
        "dataset": dataset,
        "batch_size": args.batch_size,
        "lr": args.lr,
        "epoch_log": epoch_log,
        # The cold-vs-warm attribution for the <60s target: per-program
        # compile ms + persistent-cache hit/miss (cli.run's compile_log).
        # A warm rerun should show every program cache-hit and the
        # seconds_total drop by roughly the cold compile wall time.
        "compile_cache": active_cache_dir(),
        "compile_stats": summary.get("compile_stats"),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
