"""Operator tooling for the tpu-mnist framework.

A regular package so ``python -m tools.analyzer`` and ``from
tools.analyzer import run_analysis`` resolve identically everywhere
(scripts in this directory also run standalone via their own
sys.path bootstrap, unchanged).
"""
