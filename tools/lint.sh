#!/usr/bin/env bash
# One-shot lint runner: tpumnist-lint analyzer + ruff + the tier-1 lint
# gate tests. Mirrors exactly what CI enforces:
#
#   tools/lint.sh            # all three stages
#   tools/lint.sh --fast     # analyzer only (the gate itself; the
#                            # warm findings cache makes re-runs
#                            # near-instant)
#   tools/lint.sh --changed  # analyzer only, scoped to git-changed
#                            # files PLUS their reverse dependencies
#                            # from the cross-module import graph
#
# Exit code: first failing stage's code. Ruff is optional tooling — a
# missing binary prints a SKIP (the pytest gate skips the same way).
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO"

fail=0
# Record the FIRST failing stage's code (later stages still run, but must
# not overwrite it — the analyzer's 1-vs-2 exit contract survives).
note() { if [ "$fail" -eq 0 ]; then fail=$1; fi; }

analyzer_flags=()
if [ "${1:-}" = "--changed" ]; then
  analyzer_flags+=(--changed)
fi

echo "== tpumnist-lint (tools/analyzer) =="
python -m tools.analyzer "${analyzer_flags[@]+"${analyzer_flags[@]}"}" \
  pytorch_distributed_mnist_tpu tools bench.py \
  || note $?

if [ "${1:-}" = "--fast" ] || [ "${1:-}" = "--changed" ]; then
  exit "$fail"
fi

if command -v ruff >/dev/null 2>&1; then
  echo "== ruff check =="
  ruff check --no-cache pytorch_distributed_mnist_tpu tools tests bench.py \
    || note $?
else
  echo "== ruff check: SKIP (ruff not installed) =="
fi

echo "== tier-1 lint gate (pytest -m lint) =="
JAX_PLATFORMS=cpu python -m pytest tests/ -q -m lint \
  -p no:cacheprovider || note $?

exit "$fail"
