"""On-chip flash-attention block-size sweep.

The round-3 kernel capture (tools/captured/kernels.json, 2026-07-31)
showed flash beating dense XLA attention at T=1024 (1.31x) but trailing
at T=4096 (0.86x) with the then-fixed 128 tile: 32 small fori_loop
matmuls per q-block cannot match one huge fused XLA matmul when the
(T, T) scores still fit HBM comfortably. ``flash_attention(block=...)``
now exposes the tile edge; this sweep measures fwd+bwd wall-clock per
(T, block) pair against the dense path so ``_block_sizes``'s heuristic
is a measured choice, not a guess (the hermetic suite pins numerics for
non-default blocks — tests/test_pallas_kernels.py
``test_flash_attention_block_override``).

Prints ONE JSON line; run on chip (the follow-up watcher invokes it
after the northstar warm rerun).
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))  # tools/
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--quick", action="store_true",
                   help="tiny shapes for the hermetic CPU smoke test")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from bench import configure_jax
    from bench_kernels import _timeit
    from pytorch_distributed_mnist_tpu.ops.attention import full_attention
    from pytorch_distributed_mnist_tpu.ops.pallas.flash import flash_attention

    configure_jax(jax)
    device = jax.devices()[0]

    # Same constant ~8k-token budget as bench_kernels.py so rows are
    # directly comparable with the captured kernels.json.
    configs = [(64, 2)] if args.quick else [(1024, 8), (2048, 4), (4096, 2)]
    blocks = [32] if args.quick else [128, 256, 512]
    heads, dim = (2, 16) if args.quick else (8, 128)

    def make_grad(attn):
        def loss(q, k, v):
            return jnp.sum(attn(q, k, v).astype(jnp.float32))
        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    rows = []
    for t, b in configs:
        kq, kk, kv = jax.random.split(jax.random.key(0), 3)
        shape = (b, t, heads, dim)
        q = jax.random.normal(kq, shape, jnp.bfloat16)
        k = jax.random.normal(kk, shape, jnp.bfloat16)
        v = jax.random.normal(kv, shape, jnp.bfloat16)
        dense_s = _timeit(make_grad(full_attention), (q, k, v),
                          args.reps, args.iters)
        row = {"seq_len": t, "batch": b, "dense_ms": round(dense_s * 1e3, 3)}
        for blk in blocks:
            if blk > ((t + 7) // 8) * 8:
                continue
            fn = make_grad(
                functools.partial(flash_attention, block=blk))
            s = _timeit(fn, (q, k, v), args.reps, args.iters)
            row[f"flash_b{blk}_ms"] = round(s * 1e3, 3)
            row[f"flash_b{blk}_speedup"] = round(dense_s / s, 3)
        rows.append(row)

    print(json.dumps({
        "metric": "flash_block_sweep_fwd_bwd",
        "backend": device.platform,
        "device_kind": device.device_kind,
        "heads": heads, "head_dim": dim,
        "quick": args.quick,
        "rows": rows,
    }))


if __name__ == "__main__":
    main()
