"""On-chip flash-attention block-size sweep.

Hypothesis under test: at long T a small fixed tile (128) turns the
flash kernel into many small fori_loop matmuls per q-block, which may
lose to one huge fused XLA matmul while the (T, T) scores still fit
HBM comfortably — larger tiles amortize better. The round-3 capture
that first suggested a T=4096 regression was INVALIDATED (its sync
returned before execution; see BASELINE.md and
tools/captured/kernels_r3_invalid.json), so no flash-vs-dense ratio is
currently established either way. ``flash_attention(block=...)``
exposes the tile edge; this sweep measures fwd+bwd wall-clock per
(T, block) pair against the dense path so ``_block_sizes``'s heuristic
becomes a measured choice (the hermetic suite pins numerics for
non-default blocks — tests/test_pallas_kernels.py
``test_flash_attention_block_override``).

Prints ONE JSON line; run on chip (tools/tpu_watch_r4.sh invokes it,
publication gated on exit code — a physically impossible row exits 1).
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))  # tools/
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--quick", action="store_true",
                   help="tiny shapes for the hermetic CPU smoke test")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from bench import _peak_flops, configure_jax
    from bench_kernels import (
        MeasurementInvalid,
        _fake_bounds,
        _timeit,
        check_mfu,
    )
    from pytorch_distributed_mnist_tpu.ops.attention import full_attention
    from pytorch_distributed_mnist_tpu.ops.pallas.flash import flash_attention

    configure_jax(jax)
    device = jax.devices()[0]
    peak = _peak_flops(device.device_kind)
    fakes = _fake_bounds()
    if fakes and device.platform == "tpu":
        print(json.dumps({
            "metric": "flash_block_sweep_fwd_bwd",
            "backend": device.platform,
            "invalid": f"test-only bound overrides set on a real TPU "
                       f"run: {sorted(fakes)}"}))
        sys.exit(1)

    # Same constant ~8k-token budget as bench_kernels.py so rows are
    # directly comparable with the re-captured kernels.json.
    configs = [(64, 2)] if args.quick else [(1024, 8), (2048, 4), (4096, 2)]
    blocks = [32] if args.quick else [128, 256, 512]
    heads, dim = (2, 16) if args.quick else (8, 128)

    def make_grad(attn):
        def loss(q, k, v):
            return jnp.sum(attn(q, k, v).astype(jnp.float32))
        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    out = {
        "metric": "flash_block_sweep_fwd_bwd",
        "backend": device.platform,
        "device_kind": device.device_kind,
        "heads": heads, "head_dim": dim,
        "quick": args.quick,
        "rows": [],
    }
    if fakes:
        out["fake_bounds"] = fakes  # test-only run, never evidence
    try:
        for t, b in configs:
            kq, kk, kv = jax.random.split(jax.random.key(0), 3)
            shape = (b, t, heads, dim)
            q = jax.random.normal(kq, shape, jnp.bfloat16)
            k = jax.random.normal(kk, shape, jnp.bfloat16)
            v = jax.random.normal(kv, shape, jnp.bfloat16)
            dense_s = _timeit(make_grad(full_attention), (q, k, v),
                              args.reps, args.iters)
            # Same analytic fwd+bwd matmul count as bench_kernels.py.
            flops = 12.0 * b * heads * t * t * dim
            row = {"seq_len": t, "batch": b,
                   "dense_ms": round(dense_s * 1e3, 3),
                   "dense_mfu": check_mfu(f"dense T={t}", dense_s, flops, peak)}
            for blk in blocks:
                if blk > ((t + 7) // 8) * 8:
                    continue
                fn = make_grad(
                    functools.partial(flash_attention, block=blk))
                s = _timeit(fn, (q, k, v), args.reps, args.iters)
                row[f"flash_b{blk}_ms"] = round(s * 1e3, 3)
                row[f"flash_b{blk}_speedup"] = round(dense_s / s, 3)
                row[f"flash_b{blk}_mfu"] = check_mfu(
                    f"flash_b{blk} T={t}", s, flops, peak)
            out["rows"].append(row)
    except MeasurementInvalid as exc:
        out["invalid"] = str(exc)  # rows measured pre-violation retained
        print(json.dumps(out))
        sys.exit(1)
    out["sync"] = "host_read"  # via bench_kernels._timeit (round-4 fix)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
