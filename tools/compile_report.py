"""Print the per-program compile_stats from bench/northstar artifacts.

The compile-latency subsystem (utils/compile_cache.py + the trainer's AOT
precompile) records, for every program, its compile wall ms, how many real
XLA backend compiles ran, and whether the persistent cache served it. That
lands in:

- ``bench.py`` output lines (``compile_stats`` block) -> ``BENCH_r*.json``
  and the watcher's ``tools/captured/bench.json``;
- ``tools/northstar.py`` output (``compile_stats`` + ``compile_cache``);
- any JSON file a caller passes explicitly.

This tool renders those blocks as a cold-vs-warm table so the watcher
scripts can capture a human-readable compile report the moment the chip
window opens (ISSUE satellite), and so round-over-round BENCH artifacts
can be compared at a glance.

Usage:
  python tools/compile_report.py            # newest BENCH_r*.json + capture
  python tools/compile_report.py FILE...    # specific artifact file(s)

Exit status: 0 if at least one compile_stats block was found, else 1.
"""

from __future__ import annotations

import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_lines(path: str):
    """Every JSON object found in ``path`` (one per line; tolerant of
    non-JSON lines and trailing garbage — artifacts are append-style)."""
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    obj = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(obj, dict):
                    out.append(obj)
    except OSError:
        return []
    return out


def _find_stats(obj: dict):
    """The compile_stats block of an artifact line, wherever it lives
    (top level for bench/northstar; nested under ``captured`` for a
    watcher pass-through)."""
    for holder in (obj, obj.get("captured") or {}):
        stats = holder.get("compile_stats")
        if isinstance(stats, dict) and isinstance(
                stats.get("programs"), dict):
            return stats
    return None


def default_artifacts():
    benches = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))
    paths = benches[-1:] if benches else []
    captured = os.path.join(REPO, "tools", "captured", "bench.json")
    if os.path.exists(captured):
        paths.append(captured)
    return paths


def report(paths) -> int:
    found = 0
    for path in paths:
        for obj in _load_lines(path):
            stats = _find_stats(obj)
            if stats is None:
                continue
            found += 1
            label = obj.get("metric") or obj.get("target_acc") or "run"
            backend = obj.get("backend", "?")
            when = obj.get("measured_at") or obj.get("capture_timestamp", "")
            print(f"\n{os.path.relpath(path, REPO)} — {label} "
                  f"[{backend}] {when}")
            print(f"  {'program':<24} {'compile ms':>10} {'XLA':>4} "
                  f"{'cache':>6}")
            for name, rec in sorted(stats["programs"].items()):
                hit = rec.get("persistent_cache_hit")
                cache = ("off" if hit is None else
                         "hit" if hit else "miss")
                print(f"  {name:<24} {rec.get('wall_ms', 0):>10.0f} "
                      f"{rec.get('backend_compiles', 0):>4} {cache:>6}")
            totals = stats.get("totals", {})
            print(f"  totals: {totals.get('backend_compiles', 0)} XLA "
                  f"compile(s), {totals.get('backend_compile_ms', 0):.0f} ms "
                  f"backend, {totals.get('cache_hits', 0)} hit / "
                  f"{totals.get('cache_misses', 0)} miss")
    if not found:
        print("no compile_stats blocks found (artifacts predate the "
              "compile-latency subsystem, or the runs never compiled)",
              file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    paths = list(sys.argv[1:] if argv is None else argv)
    if not paths:
        paths = default_artifacts()
    return report(paths)


if __name__ == "__main__":
    sys.exit(main())
