"""tpumnist-lint: AST-based invariant checker for the tpu-mnist codebase.

Five invariant families, each encoding an incident or asserted property
from PRs 1-4 (docs/DESIGN.md §8 maps checker -> incident):

- ``collective-symmetry``   collectives never sit under host-conditional
                            control flow (the structural-hang class)
- ``agreement-except-breadth``  exception funnels on agreement paths
                            catch broadly (the zlib.error strand class)
- ``trace-purity``          traced/lowered functions are pure: no host
                            side effects, no tracer concretization
- ``recompile-hazard``      AOT executables get arrays; jit sites
                            declare hashable config static
- ``lock-discipline``       no blocking work under engine/pool/sink
                            locks; one global acquisition order
- ``registry-drift``        fault-point registry == maybe_fault hooks
- ``marker-registry``       pytest markers used == markers registered

Run it::

    python -m tools.analyzer [--format text|json] [--baseline FILE] [paths]

or from tests (the tier-1 gate)::

    from tools.analyzer import run_analysis
    result = run_analysis(["pytorch_distributed_mnist_tpu", "tools",
                           "bench.py"])
    assert result.ok, result.findings

Pure stdlib; never imports the analyzed code.
"""

from tools.analyzer.core import (
    SCHEMA_VERSION,
    AnalysisResult,
    CheckerResult,
    Finding,
    Module,
    analyze_snippet,
    checker_registry,
    default_baseline_path,
    load_baseline,
    render_text,
    run_analysis,
)

__all__ = [
    "SCHEMA_VERSION",
    "AnalysisResult",
    "CheckerResult",
    "Finding",
    "Module",
    "analyze_snippet",
    "checker_registry",
    "default_baseline_path",
    "load_baseline",
    "render_text",
    "run_analysis",
]
