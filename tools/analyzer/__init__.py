"""tpumnist-lint: AST-based invariant checker for the tpu-mnist codebase.

Five invariant families, each encoding an incident or asserted property
from PRs 1-4 (docs/DESIGN.md §8 maps checker -> incident):

- ``collective-symmetry``   collectives never sit under host-conditional
                            control flow (the structural-hang class)
- ``agreement-except-breadth``  exception funnels on agreement paths
                            catch broadly (the zlib.error strand class)
- ``trace-purity``          traced/lowered functions are pure: no host
                            side effects, no tracer concretization
- ``recompile-hazard``      AOT executables get arrays; jit sites
                            declare hashable config static
- ``lock-discipline``       no blocking work under engine/pool/sink
                            locks; one global acquisition order
- ``registry-drift``        fault-point registry == maybe_fault hooks
- ``marker-registry``       pytest markers used == markers registered

Analyzer v2 (PRs 6-19 incident record) adds a project-wide def/call
index (``_ast_util.ProjectIndex``: import + ``self._attr = fn`` factory
resolution, reachability queries), per-file content-hash caching of
findings, a SARIF emitter, and five cross-module checkers:

- ``thread-lifecycle``      every Thread/Timer/Popen join/reap-reachable
                            on all exit paths of its owner
- ``handler-discipline``    every do_GET/do_POST branch replies exactly
                            once; body reads length-bounded
- ``generation-ordering``   installs under a lock re-compare the
                            generation/epoch counter under that lock
- ``short-read``            HTTP body reads verify Content-Length
- ``donated-reuse``         no reads of a donate_argnums argument after
                            the donating call

Run it::

    python -m tools.analyzer [--format text|json] [--baseline FILE] [paths]

or from tests (the tier-1 gate)::

    from tools.analyzer import run_analysis
    result = run_analysis(["pytorch_distributed_mnist_tpu", "tools",
                           "bench.py"])
    assert result.ok, result.findings

Pure stdlib; never imports the analyzed code.
"""

from tools.analyzer.core import (
    SCHEMA_VERSION,
    AnalysisResult,
    CheckerResult,
    Finding,
    Module,
    analyze_snippet,
    checker_registry,
    default_baseline_path,
    default_cache_path,
    load_baseline,
    render_sarif,
    render_text,
    run_analysis,
)

__all__ = [
    "SCHEMA_VERSION",
    "AnalysisResult",
    "CheckerResult",
    "Finding",
    "Module",
    "analyze_snippet",
    "checker_registry",
    "default_baseline_path",
    "default_cache_path",
    "load_baseline",
    "render_sarif",
    "render_text",
    "run_analysis",
]
