"""tpumnist-lint core: file collection, checker dispatch, baseline, output.

The analyzer is a *codebase-specific* static pass: each checker encodes an
invariant PRs 1-4 established the hard way (docs/DESIGN.md §8 maps each
one to the incident it came from). It is pure stdlib (``ast``) — it never
imports the code under analysis, so it runs in milliseconds with no jax
backend and can gate tier-1.

Baseline contract: ``baseline.json`` is a list of triaged-accepted
findings. Every entry MUST carry a non-empty ``justification`` (an entry
without one is a config error, not a suppression), and every entry must
suppress at least one current finding — a stale entry (the code it
excused is gone) fails the run, so the baseline can only shrink or be
consciously re-justified, never silently rot. Staleness is judged only
when the entry's file is part of the analyzed set (or is gone from disk
entirely): linting a single file must not condemn entries for files the
run never looked at.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

SCHEMA_VERSION = 1

#: Version salt for the on-disk findings cache — bump whenever a checker's
#: semantics change in a way file hashes cannot see.
CACHE_VERSION = 1

#: Checker ids whose findings a baseline entry may suppress. Parse errors
#: are never baselinable: an unparseable file means the analyzer saw
#: nothing, which must stay loud.
_UNBASELINABLE = {"parse-error", "usage"}


@dataclasses.dataclass
class Finding:
    """One analyzer hit: where, which invariant, what to do about it."""

    checker: str
    path: str
    line: int
    col: int
    message: str
    hint: str = ""
    symbol: str = ""  # enclosing function/class — stable baseline anchor

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        text = f"{self.path}:{self.line}:{self.col}: {self.checker}{sym}: " \
               f"{self.message}"
        if self.hint:
            text += f"\n    hint: {self.hint}"
        return text


@dataclasses.dataclass
class Module:
    """One parsed source file handed to every checker."""

    path: str  # repo-relative (posix) when a repo root is found
    tree: ast.Module
    source: str
    abspath: str = ""  # "" for in-memory snippets


@dataclasses.dataclass
class CheckerResult:
    findings: List[Finding]
    report: Optional[Dict] = None


@dataclasses.dataclass
class AnalysisResult:
    findings: List[Finding]            # NOT suppressed by the baseline
    suppressed: List[Tuple[Finding, Dict]]
    stale_baseline: List[Dict]         # entries that suppressed nothing
    baseline_problems: List[str]       # malformed entries / unreadable file
    reports: Dict[str, Dict]           # checker id -> structured report
    n_files: int = 0
    checkers: Tuple[str, ...] = ()
    paths: Tuple[str, ...] = ()
    cache_info: Optional[Dict] = None  # {"hit": bool, "files": N}

    @property
    def ok(self) -> bool:
        return not (self.findings or self.stale_baseline
                    or self.baseline_problems)

    def to_dict(self) -> Dict:
        return {
            "schema_version": SCHEMA_VERSION,
            "paths": list(self.paths),
            "checkers": list(self.checkers),
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [
                {**f.to_dict(), "justification": e.get("justification", "")}
                for f, e in self.suppressed
            ],
            "stale_baseline": list(self.stale_baseline),
            "baseline_problems": list(self.baseline_problems),
            "reports": self.reports,
            "cache": self.cache_info,
            "summary": {
                "files": self.n_files,
                "findings": len(self.findings),
                "suppressed": len(self.suppressed),
                "stale_baseline": len(self.stale_baseline),
                "ok": self.ok,
            },
        }


# ---------------------------------------------------------------------------
# File collection
# ---------------------------------------------------------------------------

_SKIP_DIRS = {"__pycache__", ".git", ".xla_cache", "node_modules"}
#: Repo-relative directories to skip. Matching these by bare name (the way
#: _SKIP_DIRS works) would silently drop any same-named SOURCE directory
#: added anywhere in the tree while the gate still reports OK — so they
#: are rooted, like the ruff exclude.
_SKIP_ROOTED = {"tools/captured"}


def _skip_dir(dirpath: str, name: str, root_cache: Dict) -> bool:
    if name in _SKIP_DIRS:
        return True
    root = find_repo_root(dirpath, root_cache)
    if root is None:
        return False
    rel = os.path.relpath(os.path.join(os.path.abspath(dirpath), name),
                          root).replace(os.sep, "/")
    return rel in _SKIP_ROOTED


def collect_files(paths: Sequence[str]) -> Tuple[List[str], List[Finding]]:
    """Expand files/directories into a sorted, de-duplicated .py list."""
    files: List[str] = []
    problems: List[Finding] = []
    root_cache: Dict[str, Optional[str]] = {}
    for p in paths:
        if os.path.isfile(p):
            files.append(p)
        elif os.path.isdir(p):
            for dirpath, dirnames, names in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if not _skip_dir(dirpath, d, root_cache))
                for name in sorted(names):
                    if name.endswith(".py"):
                        files.append(os.path.join(dirpath, name))
        else:
            problems.append(Finding(
                checker="usage", path=p, line=0, col=0,
                message=f"path does not exist: {p!r}"))
    seen, unique = set(), []
    for f in files:
        key = os.path.abspath(f)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return unique, problems


def find_repo_root(start: str,
                   _cache: Optional[Dict[str, Optional[str]]] = None,
                   ) -> Optional[str]:
    """Nearest ancestor holding a pyproject.toml — the path-normalization
    anchor (baseline paths stay stable whatever cwd invoked the tool).

    ``_cache`` (a per-RUN dict keyed by start directory) elides the
    repeated upward isfile walks when many analyzed files share a tree.
    It is never module-global: a run must see the filesystem as it is,
    not as a previous test's tmpdir left it.
    """
    cur = os.path.abspath(start)
    if os.path.isfile(cur):
        cur = os.path.dirname(cur)
    if _cache is not None and cur in _cache:
        return _cache[cur]
    start_dir = cur
    root: Optional[str] = None
    while True:
        if os.path.isfile(os.path.join(cur, "pyproject.toml")):
            root = cur
            break
        parent = os.path.dirname(cur)
        if parent == cur:
            break
        cur = parent
    if _cache is not None:
        _cache[start_dir] = root
    return root


def _normalize(path: str,
               root_cache: Optional[Dict[str, Optional[str]]] = None) -> str:
    root = find_repo_root(path, root_cache)
    ap = os.path.abspath(path)
    if root and (ap == root or ap.startswith(root + os.sep)):
        return os.path.relpath(ap, root).replace(os.sep, "/")
    return path.replace(os.sep, "/")


def parse_modules(files: Sequence[str]) \
        -> Tuple[List[Module], List[Finding]]:
    modules: List[Module] = []
    problems: List[Finding] = []
    root_cache: Dict[str, Optional[str]] = {}
    for path in files:
        norm = _normalize(path, root_cache)
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError, ValueError) as exc:
            problems.append(Finding(
                checker="parse-error", path=norm,
                line=getattr(exc, "lineno", 0) or 0, col=0,
                message=f"could not parse: {exc}"))
            continue
        modules.append(Module(path=norm, tree=tree, source=source,
                              abspath=os.path.abspath(path)))
    return modules, problems


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------

_ENTRY_KEYS = {"checker", "path", "contains", "justification"}


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def load_baseline(path: Optional[str]) -> Tuple[List[Dict], List[str]]:
    """Read + validate a baseline file; returns ``(entries, problems)``.
    A missing default baseline is an empty baseline; a missing *explicit*
    baseline is a problem."""
    if path is None:
        return [], []
    if not os.path.isfile(path):
        return [], [f"baseline file not found: {path!r}"]
    try:
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
    except (OSError, ValueError) as exc:
        return [], [f"baseline {path!r} unreadable: {exc}"]
    if not isinstance(raw, list):
        return [], [f"baseline {path!r} must be a JSON list of entries"]
    entries, problems = [], []
    for i, entry in enumerate(raw):
        if not isinstance(entry, dict) or \
                not _ENTRY_KEYS.issubset(entry.keys()):
            problems.append(
                f"baseline entry #{i} must be an object with keys "
                f"{sorted(_ENTRY_KEYS)}: got {entry!r}")
            continue
        if not str(entry.get("justification", "")).strip():
            problems.append(
                f"baseline entry #{i} ({entry.get('checker')} @ "
                f"{entry.get('path')}) has no justification — every "
                f"accepted finding must say WHY it is acceptable")
            continue
        if entry.get("checker") in _UNBASELINABLE:
            problems.append(
                f"baseline entry #{i}: {entry.get('checker')!r} findings "
                f"cannot be baselined")
            continue
        entries.append(entry)
    return entries, problems


def _entry_matches(entry: Dict, finding: Finding) -> bool:
    if entry["checker"] != finding.checker:
        return False
    if entry["path"] != finding.path:
        return False
    needle = str(entry["contains"])
    return needle in finding.message or needle == finding.symbol


def apply_baseline(findings: List[Finding], entries: List[Dict],
                   analyzed_paths: Optional[Sequence[str]] = None,
                   file_exists=None):
    """Split findings into (kept, suppressed) and report stale entries.

    An unused entry is stale only when this run actually judged it: its
    file was part of ``analyzed_paths`` (normalized; ``None`` means
    everything was), or ``file_exists`` says the file is gone entirely
    (a deleted file never re-enters the analyzed set, and its entries
    must not rot silently). Linting a path subset must not condemn
    entries for files the run never looked at.
    """
    kept: List[Finding] = []
    suppressed: List[Tuple[Finding, Dict]] = []
    used = [False] * len(entries)
    for f in findings:
        hit = None
        if f.checker not in _UNBASELINABLE:
            for i, entry in enumerate(entries):
                if _entry_matches(entry, f):
                    hit = (i, entry)
                    break
        if hit is None:
            kept.append(f)
        else:
            used[hit[0]] = True
            suppressed.append((f, hit[1]))
    judged = set(analyzed_paths) if analyzed_paths is not None else None
    stale = [entry for i, entry in enumerate(entries)
             if not used[i]
             and (judged is None or entry.get("path") in judged
                  or (file_exists is not None
                      and not file_exists(str(entry.get("path")))))]
    return kept, suppressed, stale


# ---------------------------------------------------------------------------
# Running
# ---------------------------------------------------------------------------


def checker_registry() -> Dict[str, object]:
    """Ordered ``{checker_id: module}``; import deferred so ``core`` has
    no import cycle with the checker package."""
    from tools.analyzer import checkers

    return checkers.REGISTRY


def default_cache_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        ".cache.json")


def _file_hashes(files: Sequence[str]) -> Dict[str, str]:
    """Repo-relative path -> sha256 of file bytes (unreadable files hash
    to "" so the cache can never mask a parse-error finding)."""
    hashes: Dict[str, str] = {}
    root_cache: Dict[str, Optional[str]] = {}
    for path in files:
        norm = _normalize(path, root_cache)
        try:
            with open(path, "rb") as f:
                hashes[norm] = hashlib.sha256(f.read()).hexdigest()
        except OSError:
            hashes[norm] = ""
    return hashes


def _load_cache(path: str, ids: Sequence[str], hashes: Dict[str, str],
                paths: Sequence[str]) -> Optional[Dict]:
    """The cached payload when it is valid for exactly this run: same
    cache schema, same checker list, same input paths, same file set
    with byte-identical contents. Anything else is a miss."""
    try:
        with open(path, encoding="utf-8") as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(payload, dict):
        return None
    if payload.get("cache_version") != CACHE_VERSION:
        return None
    if payload.get("schema_version") != SCHEMA_VERSION:
        return None
    if payload.get("checkers") != list(ids):
        return None
    if payload.get("paths") != [str(p) for p in paths]:
        return None
    if payload.get("files") != hashes:
        return None
    return payload


def _store_cache(path: str, ids: Sequence[str], hashes: Dict[str, str],
                 paths: Sequence[str], findings: Sequence[Finding],
                 reports: Dict[str, Dict],
                 module_paths: Sequence[str]) -> None:
    payload = {
        "cache_version": CACHE_VERSION,
        "schema_version": SCHEMA_VERSION,
        "checkers": list(ids),
        "paths": [str(p) for p in paths],
        "files": hashes,
        "findings": [f.to_dict() for f in findings],
        "reports": reports,
        "module_paths": list(module_paths),
    }
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
    except OSError:
        pass  # best effort: a cold run next time, never a failure now
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def run_analysis(
    paths: Sequence[str],
    checkers: Optional[Sequence[str]] = None,
    baseline: Optional[str] = "default",
    cache: Optional[str] = None,
    changed: Optional[Sequence[str]] = None,
) -> AnalysisResult:
    """Analyze ``paths`` with the selected checkers.

    ``baseline``: a file path, ``"default"`` (the checked-in
    ``tools/analyzer/baseline.json``), or ``None`` (no suppression).

    ``cache``: a file path for the per-file content-hash findings cache.
    A warm run on an unchanged tree (same files, same bytes, same
    checkers) skips parsing and checking entirely and replays the stored
    findings byte-for-byte; the baseline is always re-applied fresh so
    editing it never needs a cache flush.

    ``changed``: restrict *checking* to these files plus every module
    that transitively imports one of them (reverse dependencies from the
    cross-module index). The whole tree is still parsed and indexed —
    cross-module checkers must see the full call graph — but findings
    are only produced for the restricted set, and baseline staleness is
    only judged there (the existing path-subset contract).
    """
    registry = checker_registry()
    ids = list(checkers) if checkers is not None else list(registry)
    unknown = [c for c in ids if c not in registry]
    if unknown:
        raise ValueError(
            f"unknown checker(s) {unknown}; available: {list(registry)}")

    files, problems = collect_files(paths)
    cache_info: Optional[Dict] = None
    hashes: Optional[Dict[str, str]] = None
    payload: Optional[Dict] = None
    if cache is not None and changed is None:
        hashes = _file_hashes(files)
        payload = _load_cache(cache, ids, hashes, paths)
        cache_info = {"hit": payload is not None, "files": len(files)}

    if payload is not None:
        findings = list(problems) + \
            [Finding(**d) for d in payload["findings"]]
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.checker))
        reports = dict(payload["reports"])
        module_paths = list(payload["module_paths"])
    else:
        modules, parse_problems = parse_modules(files)
        findings = list(problems) + list(parse_problems)
        needs_index = changed is not None or any(
            getattr(registry[cid], "NEEDS_INDEX", False) for cid in ids)
        index = None
        if needs_index:
            from tools.analyzer._ast_util import ProjectIndex

            index = ProjectIndex(modules)
        target_modules = modules
        if changed is not None:
            root_cache: Dict[str, Optional[str]] = {}
            norm_changed = {_normalize(p, root_cache) for p in changed}
            restrict = index.reverse_dependencies(
                {m.path for m in modules if m.path in norm_changed})
            target_modules = [m for m in modules if m.path in restrict]
        reports = {}
        for cid in ids:
            mod = registry[cid]
            if getattr(mod, "NEEDS_INDEX", False):
                result: CheckerResult = mod.run(target_modules, index)
            else:
                result = mod.run(target_modules)
            findings.extend(result.findings)
            if result.report is not None:
                reports[cid] = result.report
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.checker))
        module_paths = [m.path for m in target_modules]
        if cache is not None and changed is None:
            # usage findings (bad input paths) are re-derived fresh each
            # run; everything content-derived is cached.
            _store_cache(cache, ids, hashes, paths,
                         [f for f in findings if f.checker != "usage"],
                         reports, module_paths)

    if baseline == "default":
        bl_path: Optional[str] = default_baseline_path()
        if not os.path.isfile(bl_path):
            bl_path = None
    else:
        bl_path = baseline
    entries, bl_problems = load_baseline(bl_path)

    def _entry_file_exists(path: str) -> bool:
        # Entry paths are repo-relative (or whatever the run passed in):
        # resolve against cwd, then against the baseline file's repo.
        if os.path.isfile(path):
            return True
        root = find_repo_root(bl_path) if bl_path else None
        return bool(root) and os.path.isfile(os.path.join(root, path))

    kept, suppressed, stale = apply_baseline(
        findings, entries, analyzed_paths=module_paths,
        file_exists=_entry_file_exists)

    return AnalysisResult(
        findings=kept, suppressed=suppressed, stale_baseline=stale,
        baseline_problems=bl_problems, reports=reports,
        n_files=len(module_paths), checkers=tuple(ids),
        paths=tuple(paths), cache_info=cache_info,
    )


def analyze_snippet(
    source: str,
    checkers: Optional[Sequence[str]] = None,
    filename: str = "snippet.py",
) -> List[Finding]:
    """Run checkers over one in-memory source string (the fixture-test
    entry point). No baseline, no filesystem."""
    registry = checker_registry()
    ids = list(checkers) if checkers is not None else list(registry)
    unknown = [c for c in ids if c not in registry]
    if unknown:
        raise ValueError(
            f"unknown checker(s) {unknown}; available: {list(registry)}")
    tree = ast.parse(source, filename=filename)
    module = Module(path=filename, tree=tree, source=source)
    index = None
    if any(getattr(registry[cid], "NEEDS_INDEX", False) for cid in ids):
        from tools.analyzer._ast_util import ProjectIndex

        index = ProjectIndex([module])
    findings: List[Finding] = []
    for cid in ids:
        mod = registry[cid]
        if getattr(mod, "NEEDS_INDEX", False):
            findings.extend(mod.run([module], index).findings)
        else:
            findings.extend(mod.run([module]).findings)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.checker))
    return findings


def render_text(result: AnalysisResult) -> str:
    lines: List[str] = []
    for f in result.findings:
        lines.append(f.render())
    for entry in result.stale_baseline:
        lines.append(
            f"stale baseline entry: {entry['checker']} @ {entry['path']} "
            f"(contains {entry['contains']!r}) no longer matches anything "
            f"— delete it (the code it excused is gone)")
    for problem in result.baseline_problems:
        lines.append(f"baseline problem: {problem}")
    s = result.to_dict()["summary"]
    cache_note = ""
    if result.cache_info is not None:
        cache_note = " [cache hit]" if result.cache_info.get("hit") \
            else " [cache miss]"
    lines.append(
        f"tpumnist-lint: {s['files']} files, {s['findings']} finding(s), "
        f"{s['suppressed']} baselined, {s['stale_baseline']} stale "
        f"baseline entr{'y' if s['stale_baseline'] == 1 else 'ies'} -> "
        f"{'OK' if result.ok else 'FAIL'}{cache_note}")
    return "\n".join(lines)


def render_sarif(result: AnalysisResult) -> str:
    """Minimal valid SARIF 2.1.0: one run, one rule per checker, one
    result per finding; baselined findings appear with an external
    suppression carrying the baseline justification."""
    registry = checker_registry()
    rule_ids = sorted({*result.checkers,
                       *(f.checker for f in result.findings),
                       *(f.checker for f, _ in result.suppressed)})
    rules = []
    for cid in rule_ids:
        mod = registry.get(cid)
        doc = (getattr(mod, "__doc__", "") or "").strip().splitlines()
        rules.append({
            "id": cid,
            "shortDescription": {"text": doc[0] if doc else cid},
        })

    def _sarif_result(f: Finding, entry: Optional[Dict] = None) -> Dict:
        text = f.message
        if f.hint:
            text += f" (hint: {f.hint})"
        r: Dict = {
            "ruleId": f.checker,
            "level": "error",
            "message": {"text": text},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(1, f.line),
                               "startColumn": max(1, f.col + 1)},
                },
            }],
        }
        if entry is not None:
            r["suppressions"] = [{
                "kind": "external",
                "justification": str(entry.get("justification", "")),
            }]
        return r

    sarif = {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "tpumnist-lint",
                "version": f"{SCHEMA_VERSION}.0.0",
                "rules": rules,
            }},
            "results": [_sarif_result(f) for f in result.findings]
            + [_sarif_result(f, e) for f, e in result.suppressed],
        }],
    }
    return json.dumps(sarif, indent=2, sort_keys=True)
