"""Checker: collective/agreement calls must be host-symmetric.

The invariant (runtime/supervision.py module docstring): multi-host
collectives have no timeout, so EVERY host must reach every collective —
a collective call under a ``process_index()``-conditioned branch, or in
a loop whose trip count differs per host, is a structural hang. This is
the "no host may fail alone" rule's static twin: the supervision layer
can convert a host-local *error* into an agreed exit, but nothing can
rescue a host that simply never calls the collective its peers are
blocked in.

``process_count()`` guards are symmetric (every host computes the same
world size) and are NOT flagged — ``if process_count() <= 1: return`` is
the sanctioned single-process fast path.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from tools.analyzer._ast_util import (
    call_name,
    contains_call_to,
    last_segment,
)
from tools.analyzer.core import CheckerResult, Finding, Module

CHECKER_ID = "collective-symmetry"

#: Host-side collective entry points (matched on the last dotted segment).
COLLECTIVE_CALLS = {
    "allgather_records",
    "agree",
    "_agree_phase_ok",
    "raise_if_poisoned",  # decodes an allgather every host must have run
    "process_allgather",
    "broadcast_one_to_all",
    "sync_global_devices",
}

#: Calls whose result differs per host — a branch on one is asymmetric.
HOST_DEPENDENT_CALLS = {"process_index"}


def _is_host_dependent(expr: ast.AST) -> bool:
    return contains_call_to(expr, HOST_DEPENDENT_CALLS)


def _definite_exit(body: List[ast.stmt]) -> Optional[str]:
    """``"function"``/``"break"``/``"continue"`` when the statement list
    unconditionally leaves the enclosing scope (a direct
    Return/Raise/Break/Continue — nested conditionals don't count: they
    exit only sometimes). Break and continue are distinct kinds: one arm
    breaking while the other continues still diverges the trip counts."""
    for stmt in body:
        if isinstance(stmt, (ast.Return, ast.Raise)):
            return "function"
        if isinstance(stmt, ast.Break):
            return "break"
        if isinstance(stmt, ast.Continue):
            return "continue"
    return None


class _Visitor(ast.NodeVisitor):
    def __init__(self, module: Module) -> None:
        self.module = module
        self.findings: List[Finding] = []
        self._cond_stack: List[str] = []  # human reason per open hazard
        self._symbol: Optional[str] = None
        # Set once a host-conditioned branch definitely exited on one
        # side: every host-asymmetry hazard AFTER that point, not just
        # inside the branch (the early-return form of the bug).
        self._divergent: Optional[str] = None
        # Local names bound to a process_index() result — the codebase's
        # dominant idiom is ``pid = process_index()`` then branching on
        # ``pid``, so taint flows through simple assignments.
        self._host_names: set = set()

    # -- scope handling ----------------------------------------------------

    def _visit_function(self, node) -> None:
        saved = (self._cond_stack, self._symbol, self._divergent,
                 self._host_names)
        # A nested def under a host-conditional is only *defined* there;
        # where it runs is its callers' business — fresh context.
        self._cond_stack, self._symbol = [], node.name
        self._divergent, self._host_names = None, set()
        self.generic_visit(node)
        (self._cond_stack, self._symbol, self._divergent,
         self._host_names) = saved

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    def visit_Lambda(self, node: ast.Lambda) -> None:
        saved = self._cond_stack, self._divergent, self._host_names
        self._cond_stack, self._divergent = [], None
        self._host_names = set()
        self.generic_visit(node)
        self._cond_stack, self._divergent, self._host_names = saved

    # -- host-dependence taint ---------------------------------------------

    def _host_dependent(self, expr: ast.AST) -> bool:
        if _is_host_dependent(expr):
            return True
        return any(isinstance(n, ast.Name) and n.id in self._host_names
                   for n in ast.walk(expr))

    def _bind(self, target: ast.AST, tainted: bool) -> None:
        if isinstance(target, ast.Starred):
            target = target.value
        if isinstance(target, ast.Name):
            if tainted:
                self._host_names.add(target.id)
            else:
                self._host_names.discard(target.id)  # rebound clean
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt, tainted)

    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            if isinstance(target, (ast.Tuple, ast.List)) \
                    and isinstance(node.value, (ast.Tuple, ast.List)) \
                    and len(target.elts) == len(node.value.elts) \
                    and not any(isinstance(e, ast.Starred)
                                for e in target.elts):
                # positional unpack: taint each name from ITS value, so
                # ``pid, n = process_index(), 1`` taints only pid
                for t, v in zip(target.elts, node.value.elts):
                    self._bind(t, self._host_dependent(v))
            else:
                self._bind(target, self._host_dependent(node.value))
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._bind(node.target, self._host_dependent(node.value))
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        # ``x += process_index()`` adds taint; an already-tainted target
        # stays tainted (augmented assignment folds the old value in).
        if isinstance(node.target, ast.Name) \
                and self._host_dependent(node.value):
            self._host_names.add(node.target.id)
        self.generic_visit(node)

    # -- hazard contexts ---------------------------------------------------

    def _visit_conditional(self, node, test_expr, kind: str) -> None:
        hazardous = self._host_dependent(test_expr)
        if hazardous:
            self._cond_stack.append(
                f"{kind} at line {node.lineno} conditioned on "
                f"process_index()")
        self.generic_visit(node)
        if hazardous:
            self._cond_stack.pop()

    def visit_If(self, node: ast.If) -> None:
        # Judge the test ONCE, before the branch bodies run visit_Assign
        # and mutate the taint set — re-evaluating after generic_visit
        # would let a clean rebind inside the branch hide the divergence
        # (or an assignment inside it fake one).
        hazardous = self._host_dependent(node.test)
        if hazardous:
            self._cond_stack.append(
                f"if at line {node.lineno} conditioned on "
                f"process_index()")
        self.generic_visit(node)
        if hazardous:
            self._cond_stack.pop()
        if hazardous and self._divergent is None:
            # The arms leave DIFFERENT scopes (one falls through, or one
            # exits the function while the other only exits a loop):
            # hosts part ways HERE, so every collective after this
            # statement is asymmetric — the early-return form of the
            # structural hang. (Both arms exiting the same scope is
            # symmetric: no host reaches the code after.)
            body_exit = _definite_exit(node.body)
            else_exit = _definite_exit(node.orelse)
            if body_exit != else_exit:
                # A function-exit on either side out-scopes a loop-exit:
                # the returning hosts are gone for good, so divergence
                # survives past the enclosing loop.
                kind = "function" if "function" in (body_exit, else_exit) \
                    else "loop"
                self._divergent = (kind, (
                    f"early {'return/raise' if kind == 'function' else 'break/continue'}"
                    f" under the process_index()-conditioned if at line "
                    f"{node.lineno}"))

    def _visit_loop_body(self, node) -> None:
        saved = self._divergent
        self.generic_visit(node)
        if self._divergent is not None and self._divergent[0] == "loop" \
                and self._divergent is not saved:
            # break/continue divergence ends with its loop: hosts rejoin
            # at the loop exit (a return/raise set inside persists).
            self._divergent = saved

    def visit_While(self, node: ast.While) -> None:
        hazardous = self._host_dependent(node.test)
        if hazardous:
            self._cond_stack.append(
                f"while at line {node.lineno} conditioned on "
                f"process_index()")
        self._visit_loop_body(node)
        if hazardous:
            self._cond_stack.pop()

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self._visit_conditional(node, node.test, "conditional expression")

    def visit_For(self, node: ast.For) -> None:
        hazardous = self._host_dependent(node.iter)
        if hazardous:
            self._cond_stack.append(
                f"for-loop at line {node.lineno} with a "
                f"process_index()-dependent trip count")
        self._visit_loop_body(node)
        if hazardous:
            self._cond_stack.pop()

    # -- the check ---------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        name = call_name(node)
        if last_segment(name) in COLLECTIVE_CALLS and (
                self._cond_stack or self._divergent):
            if self._cond_stack:
                where = f"under a host-dependent {self._cond_stack[-1]}"
            else:
                where = f"after an {self._divergent[1]}"
            self.findings.append(Finding(
                checker=CHECKER_ID,
                path=self.module.path,
                line=node.lineno,
                col=node.col_offset,
                symbol=self._symbol or "<module>",
                message=(
                    f"collective {name}() {where}: hosts that skip this "
                    f"call strand the hosts blocked in it (collectives "
                    f"have no timeout)"),
                hint=(
                    "run the collective on every host and branch on its "
                    "RESULT; per-host work belongs inside the branch, "
                    "the agreement outside it (see "
                    "runtime/supervision.py)"),
            ))
        self.generic_visit(node)


def run(modules: List[Module]) -> CheckerResult:
    findings: List[Finding] = []
    for module in modules:
        visitor = _Visitor(module)
        visitor.visit(module.tree)
        findings.extend(visitor.findings)
    return CheckerResult(findings=findings)
