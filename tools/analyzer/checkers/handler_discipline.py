"""handler-discipline: every HTTP handler branch answers exactly once.

The incident this encodes (docs/DESIGN.md §8): the PR 10 ``/resize``
handler had an early-return branch that never wrote a status line — the
client saw a dropped connection, which loadgen counted as a transport
error and the chaos twin diagnosed as a resize dropping in-flight
requests. The dual failure (two ``send_response`` calls on one path)
corrupts keep-alive framing just as silently.

For every ``do_*`` method of a class that defines HTTP verb handlers:

1. Every execution path must reach exactly one reply — a call that hits
   ``send_response``/``send_error`` directly OR through any helper the
   cross-module index can resolve (``self._reply``, ``self._do_resize``,
   a shared module-level ``reply(handler, ...)``). Paths that terminate
   by ``raise`` are exempt: an exception is the server loop's problem,
   not a silent drop.
2. Body reads must be length-bounded: ``self.rfile.read()`` with no size
   argument blocks forever on a keep-alive socket (the client is waiting
   for the reply while the server waits for EOF).

Loops are approximated as executing zero-or-one times and reply counts
saturate at 2 ("more than once") — handlers are short glue code, and the
approximation keeps the path walk linear.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from tools.analyzer._ast_util import (
    call_name,
    dotted_name,
    last_segment,
)
from tools.analyzer.core import CheckerResult, Finding

CHECKER_ID = "handler-discipline"
NEEDS_INDEX = True

_REPLY_TARGETS = frozenset({"send_response", "send_error"})
_VERBS = ("do_GET", "do_POST", "do_PUT", "do_DELETE", "do_HEAD",
          "do_PATCH")

#: (reply_count, kind, node) — kind is how the path ended.
_Terminal = Tuple[int, str, ast.AST]


def _cap(n: int) -> int:
    return 2 if n >= 2 else n


class _PathWalker:
    """Abstract interpretation of one handler body: propagate the set of
    possible reply counts along every path; collect terminals."""

    def __init__(self, module, classname: Optional[str], index) -> None:
        self.module = module
        self.classname = classname
        self.index = index
        self.terminals: List[_Terminal] = []

    def hits(self, node: ast.AST) -> int:
        if node is None:
            return 0
        return self.index.call_hits(node, self.module, self.classname,
                                    _REPLY_TARGETS)

    def flow(self, stmts, counts: Set[int]) -> Set[int]:
        """Returns the set of reply counts that fall through ``stmts``."""
        for stmt in stmts:
            if not counts:
                return counts
            if isinstance(stmt, ast.Return):
                n = self.hits(stmt.value)
                for c in counts:
                    self.terminals.append((_cap(c + n), "return", stmt))
                return set()
            if isinstance(stmt, ast.Raise):
                for c in counts:
                    self.terminals.append((_cap(c), "raise", stmt))
                return set()
            if isinstance(stmt, ast.If):
                pre = self.hits(stmt.test)
                entry = {_cap(c + pre) for c in counts}
                counts = self.flow(stmt.body, set(entry)) | \
                    self.flow(stmt.orelse, set(entry))
            elif isinstance(stmt, (ast.While, ast.For)):
                if isinstance(stmt, ast.While):
                    pre = self.hits(stmt.test)
                else:
                    pre = self.hits(stmt.iter)
                entry = {_cap(c + pre) for c in counts}
                once = self.flow(list(stmt.body), set(entry))
                after = entry | once
                counts = self.flow(stmt.orelse, after) if stmt.orelse \
                    else after
            elif isinstance(stmt, ast.Try):
                body_out = self.flow(stmt.body, set(counts))
                if stmt.orelse:
                    body_out = self.flow(stmt.orelse, body_out)
                handler_out: Set[int] = set()
                for h in stmt.handlers:
                    # the exception may fire before any reply in the try
                    # body landed — handlers enter at the pre-try counts
                    handler_out |= self.flow(h.body, set(counts))
                merged = body_out | handler_out
                if stmt.finalbody:
                    counts = self.flow(stmt.finalbody, merged)
                else:
                    counts = merged
            elif isinstance(stmt, ast.With):
                pre = sum(self.hits(item.context_expr)
                          for item in stmt.items)
                counts = self.flow(stmt.body,
                                   {_cap(c + pre) for c in counts})
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                continue
            else:
                n = self.hits(stmt)
                counts = {_cap(c + n) for c in counts}
        return counts


def _unbounded_body_reads(class_node: ast.ClassDef):
    for sub in ast.walk(class_node):
        if isinstance(sub, ast.Call) and \
                isinstance(sub.func, ast.Attribute) and \
                sub.func.attr == "read" and not sub.args:
            base = dotted_name(sub.func.value)
            if base and last_segment(base) == "rfile":
                yield sub


def _handler_classes(module):
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ClassDef):
            verbs = [m for m in node.body
                     if isinstance(m, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))
                     and m.name in _VERBS]
            if verbs:
                yield node, verbs


def run(modules, index) -> CheckerResult:
    findings: List[Finding] = []
    n_handlers = 0
    for module in modules:
        for class_node, verbs in _handler_classes(module):
            for method in verbs:
                n_handlers += 1
                symbol = f"{class_node.name}.{method.name}"
                walker = _PathWalker(module, class_node.name, index)
                fallthrough = walker.flow(method.body, {0})
                terminals = list(walker.terminals)
                for c in fallthrough:
                    terminals.append((c, "return", method))
                reported_drop = reported_double = False
                for count, kind, node in terminals:
                    line = getattr(node, "lineno", method.lineno)
                    col = getattr(node, "col_offset", 0)
                    if kind == "raise":
                        continue
                    if count == 0 and not reported_drop:
                        reported_drop = True
                        findings.append(Finding(
                            checker=CHECKER_ID, path=module.path,
                            line=line, col=col, symbol=symbol,
                            message=f"{symbol} has a path that returns "
                                    f"without sending any response — "
                                    f"the client sees a dropped "
                                    f"connection (the PR 10 /resize "
                                    f"shape)",
                            hint="every branch must reach "
                                 "send_response/send_error exactly "
                                 "once (helpers that call them count)"))
                    elif count >= 2 and not reported_double:
                        reported_double = True
                        findings.append(Finding(
                            checker=CHECKER_ID, path=module.path,
                            line=line, col=col, symbol=symbol,
                            message=f"{symbol} has a path that sends "
                                    f"more than one response — "
                                    f"keep-alive framing corrupts "
                                    f"silently",
                            hint="return after the first reply on "
                                 "each branch"))
            for read in _unbounded_body_reads(class_node):
                findings.append(Finding(
                    checker=CHECKER_ID, path=module.path,
                    line=read.lineno, col=read.col_offset,
                    symbol=class_node.name,
                    message="rfile.read() with no length bound blocks "
                            "forever on a keep-alive socket",
                    hint="read exactly int(self.headers['Content-"
                         "Length']) bytes"))
    return CheckerResult(findings=findings,
                         report={"handlers": n_handlers})
