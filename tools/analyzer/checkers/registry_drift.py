"""Checker: fault-injection hooks, registry, and docs cannot drift.

``runtime/supervision.py`` keeps the authoritative ``FAULT_POINTS``
registry; ``maybe_fault("<point>")`` call sites are the hooks;
``tools/chaos.py --list`` renders the registry verbatim. The invariant
(previously a point test in tests/test_supervision.py, now a thin
wrapper over this checker): every hook uses a registered literal, and
every registered point has a live hook — a registry entry whose hook was
deleted advertises an injection the chaos harness can no longer perform,
and an unregistered hook would fail ``maybe_fault``'s runtime assert on
first fire (i.e. in production, not in review).

The checker is cross-file: it only reports drift when the registry
module (the one assigning ``FAULT_POINTS``) is part of the analyzed set,
so analyzing a lone file never yields spurious "unreachable point"
noise.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from tools.analyzer._ast_util import call_name, last_segment
from tools.analyzer.core import CheckerResult, Finding, Module

CHECKER_ID = "registry-drift"

REGISTRY_NAME = "FAULT_POINTS"
HOOK_NAME = "maybe_fault"


def registry_entries(modules: List[Module]) \
        -> Optional[Tuple[Module, Dict[str, int]]]:
    """The module assigning ``FAULT_POINTS`` and its ``{key: line}`` map.
    Public: the chaos-list wrapper test reuses this exact parse."""
    for module in modules:
        for node in module.tree.body:
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
                value = node.value
            elif isinstance(node, ast.AnnAssign):
                target = node.target
                value = node.value
            else:
                continue
            if not (isinstance(target, ast.Name)
                    and target.id == REGISTRY_NAME
                    and isinstance(value, ast.Dict)):
                continue
            keys: Dict[str, int] = {}
            for key in value.keys:
                if isinstance(key, ast.Constant) and \
                        isinstance(key.value, str):
                    keys[key.value] = key.lineno
            return module, keys
    return None


def hook_sites(modules: List[Module]) \
        -> Tuple[List[Tuple[Module, ast.Call, str]],
                 List[Tuple[Module, ast.Call]]]:
    """``maybe_fault`` call sites: (literal sites, non-literal sites).
    The defining module's internal uses (the ``assert point in ...``
    body) are naturally excluded — it calls nothing named maybe_fault."""
    literal, dynamic = [], []
    for module in modules:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if last_segment(call_name(node)) != HOOK_NAME:
                continue
            if len(node.args) == 1 and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                literal.append((module, node, node.args[0].value))
            else:
                dynamic.append((module, node))
    return literal, dynamic


def run(modules: List[Module]) -> CheckerResult:
    findings: List[Finding] = []
    registry = registry_entries(modules)
    literal, dynamic = hook_sites(modules)
    for module, node in dynamic:
        findings.append(Finding(
            checker=CHECKER_ID, path=module.path, line=node.lineno,
            col=node.col_offset, symbol=HOOK_NAME,
            message=("maybe_fault() must take a single string literal "
                     "from FAULT_POINTS: a computed point name defeats "
                     "the static registry<->hook drift gate"),
            hint="inline the literal; one hook per fault point",
        ))
    if registry is None:
        return CheckerResult(
            findings=findings,
            report={"fault_points": None, "hook_sites": len(literal)})
    reg_module, keys = registry
    for module, node, point in literal:
        if point not in keys:
            findings.append(Finding(
                checker=CHECKER_ID, path=module.path, line=node.lineno,
                col=node.col_offset, symbol=HOOK_NAME,
                message=(
                    f"maybe_fault({point!r}) is not in FAULT_POINTS "
                    f"({reg_module.path}): the hook would fail its "
                    f"runtime assert on first fire, and chaos --list "
                    f"cannot advertise it"),
                hint="register the point (name -> where it fires) in "
                     "runtime/supervision.py FAULT_POINTS",
            ))
    called = {point for _m, _n, point in literal}
    for point, line in sorted(keys.items()):
        if point not in called:
            findings.append(Finding(
                checker=CHECKER_ID, path=reg_module.path, line=line,
                col=0, symbol=REGISTRY_NAME,
                message=(
                    f"FAULT_POINTS entry {point!r} has no "
                    f"maybe_fault({point!r}) hook anywhere in the "
                    f"analyzed tree: chaos --list advertises an "
                    f"injection that can never fire"),
                hint="delete the registry entry or restore the hook at "
                     "the documented site",
            ))
    return CheckerResult(
        findings=findings,
        report={"fault_points": sorted(keys), "hook_sites": len(literal)})
