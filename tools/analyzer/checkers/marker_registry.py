"""Checker: every pytest marker used is registered in pyproject.toml.

The tests/test_markers_registered.py logic as an analyzer checker (the
old test is now a thin wrapper over this module): an unregistered marker
silently breaks ``-m`` selection — a misspelled ``@pytest.mark.serv``
test runs in the default profile AND is invisible to the marker-filtered
profiles, with nothing but a scrolling warning to show for it.

For each analyzed file that uses ``pytest.mark.<name>``, the governing
``pyproject.toml`` is the nearest one up the directory tree from that
file; files with no pyproject above them are skipped (fixture snippets
pass an explicit registry instead, via ``check_usage``).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from tools.analyzer.core import (
    CheckerResult,
    Finding,
    Module,
    find_repo_root,
)

CHECKER_ID = "marker-registry"

#: Markers pytest itself defines; everything else must be declared.
BUILTIN_MARKERS = {"parametrize", "skip", "skipif", "xfail", "usefixtures",
                   "filterwarnings", "tryfirst", "trylast"}


def registered_markers(pyproject_text: str) -> Set[str]:
    """Parse ``[tool.pytest.ini_options] markers`` without tomllib
    (python 3.10): quoted "name: description" strings in the list."""
    section = re.search(r"markers\s*=\s*\[(.*?)\]", pyproject_text, re.S)
    if not section:
        return set()
    # "name: description", "name(args): description", or a bare "name" —
    # pytest accepts a description-less registration.
    return set(re.findall(r'"\s*([A-Za-z_]\w*)\s*(?:[:(][^"]*)?"',
                          section.group(1)))


def used_markers(module: Module) -> List[Tuple[str, int, int]]:
    """``pytest.mark.<name>`` attribute uses: (name, line, col)."""
    out = []
    for node in ast.walk(module.tree):
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Attribute)
                and node.value.attr == "mark"
                and isinstance(node.value.value, ast.Name)
                and node.value.value.id == "pytest"):
            out.append((node.attr, node.lineno, node.col_offset))
    return out


def _governing_pyproject(path: str,
                         root_cache: Optional[Dict] = None) -> Optional[str]:
    root = find_repo_root(path, root_cache)
    return os.path.join(root, "pyproject.toml") if root else None


def check_usage(module: Module, registered: Set[str]) -> List[Finding]:
    findings = []
    for name, line, col in used_markers(module):
        if name in BUILTIN_MARKERS or name in registered:
            continue
        findings.append(Finding(
            checker=CHECKER_ID, path=module.path, line=line, col=col,
            symbol=name,
            message=(
                f"pytest marker {name!r} is not registered in "
                f"[tool.pytest.ini_options] markers: -m selection "
                f"silently mismatches and the test drifts between "
                f"profiles"),
            hint="register it in pyproject.toml markers "
                 "(\"name: description\") or fix the spelling",
        ))
    return findings


def run(modules: List[Module]) -> CheckerResult:
    findings: List[Finding] = []
    cache: Dict[str, Set[str]] = {}
    root_cache: Dict[str, Optional[str]] = {}
    n_uses = 0
    for module in modules:
        uses = used_markers(module)
        if not uses:
            continue
        n_uses += len(uses)
        if not module.abspath:
            continue  # in-memory snippet: no governing config
        pyproject = _governing_pyproject(module.abspath, root_cache)
        if pyproject is None:
            continue  # no governing config: fixture context
        if pyproject not in cache:
            try:
                with open(pyproject, encoding="utf-8") as f:
                    cache[pyproject] = registered_markers(f.read())
            except OSError:
                cache[pyproject] = set()
        findings.extend(check_usage(module, cache[pyproject]))
    return CheckerResult(findings=findings,
                         report={"marker_uses": n_uses})
