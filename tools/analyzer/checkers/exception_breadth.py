"""Checker: exception funnels on agreement paths must catch broadly.

The zlib-strand class (ADVICE round 5, fixed in PR 1): a host-local
failure between a fault point and its agreement collective is REPORTED to
the peers via that collective — so the ``try`` that converts "this host
failed" into "this host votes E" must funnel *every* failure. A narrow
tuple (``except (OSError, ValueError)``) leaks any unanticipated type
(``zlib.error`` was the historical one: corrupt mid-stream gzip, not an
OSError subclass) past the funnel, and the host dies alone while its
peers block forever in the timeout-less collective.

Rule: inside any function whose scope (nested defs included) performs an
agreement collective, an ``except`` that

- names specific types rather than ``Exception``/``BaseException``/bare,
- swallows (its handler body never raises), and
- guards a try body that actually calls something (an attribute-poke
  ``try`` has nothing to leak)

is flagged. Handlers that re-raise are translators, not funnels — they
may be as narrow as they like.
"""

from __future__ import annotations

import ast
from typing import List

from tools.analyzer._ast_util import (
    body_contains_any_call,
    body_contains_raise,
    call_name,
    handler_type_names,
    iter_functions,
    last_segment,
)
from tools.analyzer.core import CheckerResult, Finding, Module

CHECKER_ID = "agreement-except-breadth"

#: A call to any of these makes the enclosing function an agreement scope.
AGREEMENT_CALLS = {"allgather_records", "agree", "_agree_phase_ok"}

BROAD = {"Exception", "BaseException"}


def _subtree_has_agreement(fn: ast.AST) -> bool:
    for node in ast.walk(fn):  # nested defs included on purpose
        if isinstance(node, ast.Call) and \
                last_segment(call_name(node)) in AGREEMENT_CALLS:
            return True
    return False


def _agreement_scopes(tree: ast.AST):
    """Outermost functions whose subtree (nested defs included) performs
    an agreement collective; inner defs are checked as part of the outer
    scope, not re-yielded."""
    claimed = set()
    for fn, qual, _cls in iter_functions(tree):
        if id(fn) in claimed:
            continue
        if _subtree_has_agreement(fn):
            yield fn, qual
            for sub in ast.walk(fn):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    claimed.add(id(sub))


def run(modules: List[Module]) -> CheckerResult:
    findings: List[Finding] = []
    for module in modules:
        seen = set()
        for fn, qual in _agreement_scopes(module.tree):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Try) or id(node) in seen:
                    continue
                seen.add(id(node))
                if not body_contains_any_call(node.body):
                    continue  # nothing fallible enough to leak
                handler_names = [handler_type_names(h)
                                 for h in node.handlers]
                broad_at = [i for i, names in enumerate(handler_names)
                            if not names  # bare except
                            or any(last_segment(n) in BROAD for n in names)]
                for i, handler in enumerate(node.handlers):
                    names = handler_names[i]
                    if i in broad_at:
                        continue  # itself the funnel
                    if broad_at:
                        # A broad sibling means nothing leaks this try:
                        # after the narrow handler it funnels everything
                        # the narrow one misses (special-case-then-
                        # funnel); before it, it catches everything
                        # FIRST (the narrow handler is dead code, a ruff
                        # problem — not a strand hazard).
                        continue
                    if body_contains_raise(handler.body):
                        continue  # translator, not a swallow
                    caught = ", ".join(names)
                    findings.append(Finding(
                        checker=CHECKER_ID,
                        path=module.path,
                        line=handler.lineno,
                        col=handler.col_offset,
                        symbol=qual,
                        message=(
                            f"narrow swallowing except ({caught}) on an "
                            f"agreement path: any exception type outside "
                            f"this tuple bypasses the funnel and this "
                            f"host dies alone while peers block in the "
                            f"timeout-less agreement collective (the "
                            f"zlib.error strand class)"),
                        hint=(
                            "catch Exception — the agreement already "
                            "reports per-host failure with the detail "
                            "string — or re-raise inside the handler; if "
                            "the narrowness is load-bearing, baseline it "
                            "with a justification"),
                    ))
        del seen
    return CheckerResult(findings=findings)
