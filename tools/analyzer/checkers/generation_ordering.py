"""generation-ordering: re-compare the counter under the lock you
install under.

The incident record (docs/DESIGN.md §8): the PR 4 hot-reload swap
installed params that were placed OUTSIDE the lock; without re-comparing
the epoch UNDER the lock before the install, a slow old fan-out could
overwrite a newer model (``serve/engine.py swap_params``). PR 19 hit the
identical shape one layer up: a response computed against generation G
must not be inserted into the cache after the generation bumped to G+1
(``serve/economics.py ResponseCache.put``). Both fixes are the same
sentence: *snapshot the counter under the lock, compute outside, then
re-compare under the lock immediately before the install.*

Mechanically, for every class that owns BOTH a lock attribute and a
generation-ish counter (an attribute or parameter matching
``generation|epoch|version``):

- a method that *receives* a counter as a parameter (``epoch=``,
  ``generation=`` — the caller-snapshot shape both incidents share) and
  then assigns non-counter state to ``self`` (or into a ``self``
  container) inside a ``with self.<lock>`` block must ALSO compare a
  counter inside that block — directly, or inside any callee the
  cross-module index can resolve from the block (the
  ``engine -> pool -> watcher`` fan-outs are checked end-to-end this
  way).
- ``AugAssign`` bumps of the counter itself are exempt (that IS the
  generation bump). Methods with no counter parameter are exempt even
  when they read/bump ``self``'s own counter: they are the generation
  *producers* (resize/regroup bump the counter as part of the install),
  not stale consumers racing it — and plain stats updates under a lock
  are not this checker's business either.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from tools.analyzer._ast_util import (
    call_name,
    dotted_name,
    function_param_names,
    iter_functions,
    last_segment,
    module_name,
    walk_body_in_scope,
    walk_in_scope,
)
from tools.analyzer.core import CheckerResult, Finding

CHECKER_ID = "generation-ordering"
NEEDS_INDEX = True

_COUNTER_RE = re.compile(r"(generation|epoch|version)", re.IGNORECASE)
_LOCK_CTORS = {"Lock", "RLock", "Condition"}


def _class_locks_and_counters(class_node: ast.ClassDef):
    locks: Set[str] = set()
    counters: Set[str] = set()
    for sub in ast.walk(class_node):
        target = None
        if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
            target = sub.targets[0]
            value = sub.value
        elif isinstance(sub, ast.AugAssign):
            target = sub.target
            value = None
        else:
            continue
        if not (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"):
            continue
        if value is not None and isinstance(value, ast.Call) and \
                last_segment(call_name(value)) in _LOCK_CTORS:
            locks.add(target.attr)
        if _COUNTER_RE.search(target.attr):
            counters.add(target.attr)
    return locks, counters


def _counter_tokens(node: ast.AST, counters: Set[str],
                    params: Set[str]) -> bool:
    """Does ``node`` mention a counter attribute or parameter?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in counters and \
                isinstance(sub.value, ast.Name) and sub.value.id == "self":
            return True
        if isinstance(sub, ast.Name) and sub.id in params:
            return True
    return False


def _block_compares_counter(block: ast.With, counters: Set[str],
                            params: Set[str], module,
                            classname: Optional[str], index) -> bool:
    for sub in walk_body_in_scope(block.body):
        if isinstance(sub, ast.Compare) and \
                _counter_tokens(sub, counters, params):
            return True
    # A callee invoked inside the block may own the compare (the pool
    # delegates the ordering rule to each engine's swap_params).
    for sub in walk_body_in_scope(block.body):
        if not isinstance(sub, ast.Call):
            continue
        for fq in index.resolve_call(sub, module, classname):
            info = index.functions.get(fq)
            if info is None:
                continue
            callee_params = {p for p in function_param_names(info.node)
                             if _COUNTER_RE.search(p)}
            for inner in walk_body_in_scope(info.node.body):
                if isinstance(inner, ast.Compare) and _counter_tokens(
                        inner, counters | _any_counter_attrs(info),
                        callee_params):
                    return True
    return False


def _any_counter_attrs(info) -> Set[str]:
    out: Set[str] = set()
    for sub in walk_in_scope(info.node):
        if isinstance(sub, ast.Attribute) and _COUNTER_RE.search(sub.attr):
            out.add(sub.attr)
    return out


def _installs_in_block(block: ast.With, counters: Set[str],
                       locks: Set[str]):
    for sub in walk_body_in_scope(block.body):
        if not isinstance(sub, ast.Assign):
            continue
        for target in sub.targets:
            attr = None
            if isinstance(target, ast.Attribute):
                attr_node = target
            elif isinstance(target, ast.Subscript) and \
                    isinstance(target.value, ast.Attribute):
                attr_node = target.value
            else:
                continue
            if not (isinstance(attr_node.value, ast.Name)
                    and attr_node.value.id == "self"):
                continue
            attr = attr_node.attr
            if attr in counters or attr in locks:
                continue  # stamping the counter IS the protocol
            yield sub, attr


def _lock_blocks(fn: ast.AST, locks: Set[str]):
    for sub in walk_body_in_scope(fn.body):
        if isinstance(sub, ast.With):
            for item in sub.items:
                d = dotted_name(item.context_expr)
                if d and d.startswith("self.") and \
                        d.split(".")[1] in locks:
                    yield sub
                    break


def run(modules, index) -> CheckerResult:
    findings: List[Finding] = []
    n_guarded = 0
    for module in modules:
        modname = module_name(module.path)
        class_info: Dict[str, tuple] = {}
        for fn, qual, classname in iter_functions(module.tree):
            if classname is None:
                continue
            if classname not in class_info:
                node = index.class_node(modname, classname)
                if node is None:
                    continue
                class_info[classname] = _class_locks_and_counters(node)
            locks, counters = class_info[classname]
            if not locks or not counters:
                continue
            params = {p for p in function_param_names(fn)
                      if _COUNTER_RE.search(p)}
            if not params:
                # No caller-supplied counter: this method is either the
                # generation PRODUCER (resize/regroup bump the counter
                # themselves) or counter-oblivious; neither is the
                # stale-consumer race this checker encodes.
                continue
            for block in _lock_blocks(fn, locks):
                installs = list(_installs_in_block(block, counters,
                                                   locks))
                if not installs:
                    continue
                n_guarded += 1
                if _block_compares_counter(block, counters, params,
                                           module, classname, index):
                    continue
                stmt, attr = installs[0]
                findings.append(Finding(
                    checker=CHECKER_ID, path=module.path,
                    line=stmt.lineno, col=stmt.col_offset,
                    symbol=f"{classname}.{fn.name}",
                    message=f"self.{attr} installed under the lock "
                            f"without re-comparing "
                            f"{'/'.join(sorted(counters))} — a stale "
                            f"computation can overwrite newer state "
                            f"(the PR 4 swap_params / PR 19 stale-"
                            f"cache-insert shape)",
                    hint="snapshot the counter under the lock, compute "
                         "outside, re-compare under the lock "
                         "immediately before the install"))
    return CheckerResult(findings=findings,
                         report={"guarded_installs": n_guarded})
