"""Checker registry for tpumnist-lint.

Ordered: the order here is the order checkers run and the order
``--list-checkers`` prints. Each module exposes ``CHECKER_ID`` and
``run(modules) -> CheckerResult`` — or, with ``NEEDS_INDEX = True``,
``run(modules, index)`` taking the project-wide
:class:`~tools.analyzer._ast_util.ProjectIndex` (the analyzer v2
cross-module checkers).
"""

from __future__ import annotations

from tools.analyzer.checkers import (
    collective_symmetry,
    donated_reuse,
    exception_breadth,
    generation_ordering,
    handler_discipline,
    lock_discipline,
    marker_registry,
    recompile_hazard,
    registry_drift,
    short_read,
    thread_lifecycle,
    trace_purity,
)

REGISTRY = {
    mod.CHECKER_ID: mod
    for mod in (
        collective_symmetry,
        exception_breadth,
        trace_purity,
        recompile_hazard,
        lock_discipline,
        registry_drift,
        marker_registry,
        thread_lifecycle,
        handler_discipline,
        generation_ordering,
        short_read,
        donated_reuse,
    )
}

__all__ = ["REGISTRY"]
