"""Checker registry for tpumnist-lint.

Ordered: the order here is the order checkers run and the order
``--list-checkers`` prints. Each module exposes ``CHECKER_ID`` and
``run(modules) -> CheckerResult``.
"""

from __future__ import annotations

from tools.analyzer.checkers import (
    collective_symmetry,
    exception_breadth,
    lock_discipline,
    marker_registry,
    recompile_hazard,
    registry_drift,
    trace_purity,
)

REGISTRY = {
    mod.CHECKER_ID: mod
    for mod in (
        collective_symmetry,
        exception_breadth,
        trace_purity,
        recompile_hazard,
        lock_discipline,
        registry_drift,
        marker_registry,
    )
}

__all__ = ["REGISTRY"]
