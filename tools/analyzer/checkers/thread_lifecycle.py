"""thread-lifecycle: every spawned Thread/Timer/Popen must be reapable.

The incident record this encodes (docs/DESIGN.md §8):

- PR 6: the input-feeder thread (``data/staging.py``) originally outlived
  its epoch — ``close()`` had to grow an explicit ``join()`` so a feeder
  blocked on a full queue could not keep reading a dataset the trainer
  had already abandoned.
- PR 10: chaos twins spawned loadgen subprocesses and reaped them only
  on the success path; a ``communicate(timeout=...)`` expiry propagated
  past the reap and left an orphan loadgen hammering a server the twin
  was about to kill.

Rules (each with its exemption surface):

1. ``self.X = Thread(...)``: some method of the owning class must call a
   lifecycle method (``join``/``cancel``) through ``self.X``. The
   attribute handle is the owner's promise of deterministic teardown, so
   ``daemon=True`` does NOT exempt it — a daemon feeder still holds the
   dataset hostage until the interpreter dies (the PR 6 lesson).
2. A thread bound to a local: the owner must join it, or visibly hand it
   off (return/yield it, store it on ``self``, pass it to a call, put it
   in a container), or it must be ``daemon=True`` with a sentinel-shaped
   target (the target loops on ``Event.wait``/``is_set`` — a service
   loop with an explicit stop signal).
3. ``Thread(...).start()`` with no binding at all: ``daemon=True`` only.
4. ``Popen``: the same binding shapes, but the reap (``wait`` /
   ``communicate`` / ``kill`` / ``terminate``) must be *protected* —
   inside a ``finally`` or ``except`` block — because the PR 10 orphan
   was precisely an inline ``communicate(timeout=)`` whose expiry raised
   past it. ``with Popen(...)`` is exempt (the context manager waits);
   a container of Popens needs a protected reap loop over it.
5. A daemon ``Timer`` is exempt everywhere: it self-terminates after its
   interval by construction (the watchdog hard-exit shape).

Everything here is syntactic and owner-scoped: a handle that escapes the
creating scope is the *recipient's* problem (checked where it lands, if
it lands in an attribute), never silently this checker's.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.analyzer._ast_util import (
    call_name,
    dotted_name,
    iter_functions,
    last_segment,
    module_name,
    walk_body_in_scope,
)
from tools.analyzer.core import CheckerResult, Finding

CHECKER_ID = "thread-lifecycle"
NEEDS_INDEX = True

_THREAD_CTORS = {"Thread", "Timer"}
_POPEN_CTORS = {"Popen"}
_THREAD_LIFECYCLE = {"join", "cancel"}
_POPEN_LIFECYCLE = {"wait", "communicate", "kill", "terminate"}
_SENTINEL_CALLS = {"wait", "is_set"}


def _is_creation(node: ast.AST) -> Optional[str]:
    """'thread' / 'popen' when ``node`` constructs one, else None."""
    if not isinstance(node, ast.Call):
        return None
    seg = last_segment(call_name(node))
    if seg in _THREAD_CTORS:
        return "thread"
    if seg in _POPEN_CTORS:
        return "popen"
    return None


def _kw(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _is_daemon(call: ast.Call, fn: ast.AST,
               bound_name: Optional[str]) -> bool:
    v = _kw(call, "daemon")
    if isinstance(v, ast.Constant) and v.value is True:
        return True
    if bound_name is None:
        return False
    # `t.daemon = True` after construction (the Timer idiom).
    for sub in walk_body_in_scope(fn.body):
        if isinstance(sub, ast.Assign) and len(sub.targets) == 1 and \
                dotted_name(sub.targets[0]) == f"{bound_name}.daemon" and \
                isinstance(sub.value, ast.Constant) and \
                sub.value.value is True:
            return True
    return False


def _target_expr(call: ast.Call) -> Optional[ast.expr]:
    v = _kw(call, "target")
    if v is not None:
        return v
    if call.args:
        return call.args[0]
    return None


def _has_sentinel_loop(fn: ast.AST) -> bool:
    for sub in ast.walk(fn):
        if isinstance(sub, ast.While):
            for t in ast.walk(sub.test):
                if isinstance(t, ast.Call) and \
                        last_segment(call_name(t)) in _SENTINEL_CALLS:
                    return True
    return False


def _sentinel_target(call: ast.Call, fn: ast.AST, module, classname,
                     index) -> bool:
    """True when the Thread's target resolves to a function whose main
    loop polls a stop signal (``while not stop.wait(...)`` & friends)."""
    target = _target_expr(call)
    if target is None:
        return False
    if isinstance(target, ast.Lambda):
        return False
    name = dotted_name(target)
    if not name:
        return False
    # Local def in the spawning function first, then the project index.
    for sub in ast.walk(fn):
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                sub.name == last_segment(name):
            return _has_sentinel_loop(sub)
    fqs = index.resolve(name, module_name(module.path), classname,
                        module.path)
    if not fqs and "." in name:
        fqs = index.by_name.get(last_segment(name), [])[:4]
    for fq in fqs:
        info = index.functions.get(fq)
        if info is not None and _has_sentinel_loop(info.node):
            return True
    return False


def _parent_map(root: ast.AST) -> Dict[int, ast.AST]:
    parents: Dict[int, ast.AST] = {}
    for node in ast.walk(root):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    return parents


def _protected_nodes(fn_body: List[ast.stmt]) -> Set[int]:
    """ids of every in-scope node inside an ``except`` handler or a
    ``finally`` block — where a Popen reap counts as exception-safe."""
    out: Set[int] = set()
    for sub in walk_body_in_scope(fn_body):
        if isinstance(sub, ast.Try):
            for h in sub.handlers:
                for n in walk_body_in_scope(h.body):
                    out.add(id(n))
            for n in walk_body_in_scope(sub.finalbody):
                out.add(id(n))
    return out


def _name_reads(fn_body: List[ast.stmt], name: str):
    for sub in walk_body_in_scope(fn_body):
        if isinstance(sub, ast.Name) and sub.id == name and \
                isinstance(sub.ctx, ast.Load):
            yield sub


def _escapes(fn_body: List[ast.stmt], name: str, creation: ast.Call,
             parents: Dict[int, ast.AST]) -> bool:
    """The handle leaves this scope: returned/yielded, aliased into
    another binding, passed to a call. The recipient owns the lifecycle
    then. Only the handle ITSELF escaping counts — a path that climbs
    through anything but container/packing literals is a *use* of the
    handle (``out, _ = p.communicate()`` reads p's method, it does not
    hand p off), never an escape — and neither is a builtin that only
    inspects (``len(procs)``, ``enumerate(procs)``)."""
    _PACKING = (ast.Tuple, ast.List, ast.Set, ast.Dict, ast.Starred)
    _INSPECTORS = {"len", "enumerate", "sorted", "reversed", "zip",
                   "any", "all", "sum", "min", "max", "iter", "next",
                   "repr", "str", "print", "id", "bool"}
    for read in _name_reads(fn_body, name):
        cur: Optional[ast.AST] = read
        packed = True  # path so far is the bare handle or literal packs
        while cur is not None:
            parent = parents.get(id(cur))
            if isinstance(parent, (ast.Return, ast.Yield, ast.YieldFrom)):
                if packed:
                    return True
                break
            if isinstance(parent, ast.Assign):
                if packed and cur is parent.value:
                    return True  # aliased / packed into another binding
                break
            if isinstance(parent, ast.Call) and parent is not creation:
                if packed and (cur in parent.args
                               or cur in parent.keywords):
                    if not (isinstance(parent.func, ast.Name) and
                            parent.func.id in _INSPECTORS):
                        return True
            if isinstance(parent, ast.stmt):
                break
            if not isinstance(parent, _PACKING + (ast.keyword,)):
                packed = False
            cur = parent
    return False


def _lifecycle_calls(root_body: List[ast.stmt], name: str,
                     lifecycle: Set[str]):
    """Calls like ``name.join()`` / ``name[0].kill()`` in ``root_body``."""
    for sub in walk_body_in_scope(root_body):
        if isinstance(sub, ast.Call) and \
                isinstance(sub.func, ast.Attribute) and \
                sub.func.attr in lifecycle:
            base = sub.func.value
            for n in ast.walk(base):
                if isinstance(n, ast.Name) and n.id == name:
                    yield sub
                    break


def _attr_lifecycle_calls(class_node: ast.ClassDef, attr: str,
                          lifecycle: Set[str]):
    """Calls reaching a lifecycle method through ``self.<attr>`` anywhere
    in the class (``self.X.join()``, ``self.X.pop(n).join()``, ...)."""
    for sub in ast.walk(class_node):
        if isinstance(sub, ast.Call) and \
                isinstance(sub.func, ast.Attribute) and \
                sub.func.attr in lifecycle:
            for n in ast.walk(sub.func.value):
                if isinstance(n, ast.Attribute) and n.attr == attr and \
                        isinstance(n.value, ast.Name) and \
                        n.value.id == "self":
                    yield sub
                    break


def _container_reaped(fn_body: List[ast.stmt], container: str,
                      lifecycle: Set[str], protected: Set[int],
                      require_protected: bool) -> bool:
    """A loop/comprehension over ``container`` whose target gets a
    lifecycle call — the ``for t in threads: t.join()`` shape."""
    for sub in walk_body_in_scope(fn_body):
        if isinstance(sub, ast.For) and isinstance(sub.target, ast.Name):
            names = {n.id for n in ast.walk(sub.iter)
                     if isinstance(n, ast.Name)}
            if container not in names:
                continue
            for call in _lifecycle_calls(sub.body, sub.target.id,
                                         lifecycle):
                if not require_protected or id(call) in protected or \
                        id(sub) in protected:
                    return True
    return False


def _classify(creation: ast.Call, parents: Dict[int, ast.AST]
              ) -> Tuple[str, Optional[str]]:
    """(shape, binding) for one construction site. Shapes:
    'with' | 'local' | 'attr' | 'container' | 'anon' | 'escape' |
    'orphan'."""
    node: ast.AST = creation
    while True:
        parent = parents.get(id(node))
        if parent is None:
            return "escape", None
        if isinstance(parent, ast.withitem):
            return "with", None
        if isinstance(parent, ast.Attribute) and parent.value is node:
            outer = parents.get(id(parent))
            if isinstance(outer, ast.Call) and outer.func is parent:
                if parent.attr == "start":
                    return "anon", None
                return "escape", None  # Popen(...).pid and such
            return "escape", None
        if isinstance(parent, ast.Assign):
            if len(parent.targets) != 1:
                return "escape", None
            t = parent.targets[0]
            if isinstance(t, ast.Name):
                if isinstance(parent.value, (ast.Tuple, ast.List,
                                             ast.ListComp, ast.SetComp,
                                             ast.GeneratorExp)):
                    return "container", t.id
                return "local", t.id
            d = dotted_name(t)
            if d and head_segment_is_self(d):
                return "attr", d.split(".")[1]
            if isinstance(t, ast.Subscript):
                base = dotted_name(t.value)
                if base and head_segment_is_self(base):
                    return "attr", base.split(".")[1]
                if base:
                    return "container", base.split(".")[0]
            return "escape", None
        if isinstance(parent, ast.keyword):
            outer = parents.get(id(parent))
            if isinstance(outer, ast.Call):
                return "escape", None  # f(proc=Popen(...)): handed off
        if isinstance(parent, ast.Call) and (
                node in parent.args or
                any(kw.value is node for kw in parent.keywords)):
            fname = parent.func
            if isinstance(fname, ast.Attribute) and \
                    fname.attr in ("append", "add", "insert") and \
                    isinstance(fname.value, ast.Name):
                return "container", fname.value.id
            return "escape", None
        if isinstance(parent, (ast.Return, ast.Yield, ast.YieldFrom)):
            return "escape", None
        if isinstance(parent, ast.Expr):
            return "orphan", None
        if isinstance(parent, ast.stmt):
            return "escape", None
        node = parent


def head_segment_is_self(dotted: str) -> bool:
    return dotted.split(".", 1)[0] == "self" and dotted.count(".") >= 1


def _fn_findings(fn_body: List[ast.stmt], fn: ast.AST, module,
                 classname: Optional[str], symbol: str, index,
                 class_node: Optional[ast.ClassDef]) -> List[Finding]:
    parents = _parent_map(fn)
    protected = _protected_nodes(fn_body)
    findings: List[Finding] = []
    for sub in walk_body_in_scope(fn_body):
        kind = _is_creation(sub)
        if kind is None:
            continue
        shape, binding = _classify(sub, parents)
        ctor = last_segment(call_name(sub))
        daemon = _is_daemon(sub, fn, binding)
        if shape in ("with", "escape"):
            continue
        if kind == "thread" and ctor == "Timer" and daemon:
            continue  # a daemon Timer self-terminates by construction
        lifecycle = _THREAD_LIFECYCLE if kind == "thread" \
            else _POPEN_LIFECYCLE
        if shape == "anon":
            if kind == "thread" and daemon:
                continue
            findings.append(Finding(
                checker=CHECKER_ID, path=module.path, line=sub.lineno,
                col=sub.col_offset, symbol=symbol,
                message=f"anonymous {ctor}(...).start() can never be "
                        f"joined or reaped",
                hint="bind the handle and join/reap it, or make it a "
                     "daemon with a sentinel-stop loop"))
            continue
        if shape == "orphan":
            findings.append(Finding(
                checker=CHECKER_ID, path=module.path, line=sub.lineno,
                col=sub.col_offset, symbol=symbol,
                message=f"{ctor}(...) constructed and discarded — the "
                        f"child outlives every handle to it",
                hint="keep the handle and reap it (join/wait), or use "
                     "`with Popen(...)`"))
            continue
        if shape == "attr":
            if class_node is not None and any(True for _ in
                    _attr_lifecycle_calls(class_node, binding, lifecycle)):
                continue
            findings.append(Finding(
                checker=CHECKER_ID, path=module.path, line=sub.lineno,
                col=sub.col_offset, symbol=symbol,
                message=f"self.{binding} holds a {ctor} but no method of "
                        f"the class ever join/reaps it (the PR 6 feeder-"
                        f"leak shape)",
                hint=f"call self.{binding}.join()/wait() from close()/"
                     f"stop(); daemon=True does not excuse an attribute "
                     f"handle"))
            continue
        # local or container binding
        satisfied = False
        if shape == "local":
            for call in _lifecycle_calls(fn_body, binding, lifecycle):
                if kind == "thread" or id(call) in protected:
                    satisfied = True
                    break
            if not satisfied and _escapes(fn_body, binding, sub, parents):
                continue
        else:  # container
            if _container_reaped(fn_body, binding, lifecycle, protected,
                                 require_protected=(kind == "popen")):
                satisfied = True
            elif _escapes(fn_body, binding, sub, parents):
                continue
        if satisfied:
            continue
        if kind == "thread" and daemon and \
                _sentinel_target(sub, fn, module, classname, index):
            continue
        if kind == "popen":
            has_any = any(True for _ in _lifecycle_calls(
                fn_body, binding or "", _POPEN_LIFECYCLE)) or (
                shape == "container" and _container_reaped(
                    fn_body, binding or "", _POPEN_LIFECYCLE, protected,
                    require_protected=False))
            if has_any:
                msg = (f"Popen bound to {binding!r} is reaped only on "
                       f"the happy path — an exception (communicate "
                       f"timeout, failed probe) orphans the child (the "
                       f"PR 10 orphaned-loadgen shape)")
                hint = "move the kill()/wait() into a finally/except " \
                       "block so every exit path reaps it"
            else:
                msg = f"Popen bound to {binding!r} is never reaped"
                hint = "wait()/kill() it in a finally block, or use " \
                       "`with Popen(...)`"
        else:
            msg = (f"{ctor} bound to {binding!r} is never joined and "
                   f"has no daemon sentinel loop")
            hint = "join it before the owner returns, or make it " \
                   "daemon=True with a target that polls a stop Event"
        findings.append(Finding(
            checker=CHECKER_ID, path=module.path, line=sub.lineno,
            col=sub.col_offset, symbol=symbol, message=msg, hint=hint))
    return findings


def run(modules, index) -> CheckerResult:
    findings: List[Finding] = []
    n_sites = 0
    for module in modules:
        modname = module_name(module.path)
        for fn, qual, classname in iter_functions(module.tree):
            class_node = index.class_node(modname, classname) \
                if classname else None
            findings.extend(_fn_findings(
                fn.body, fn, module, classname, qual, index, class_node))
            n_sites += sum(1 for s in walk_body_in_scope(fn.body)
                           if _is_creation(s))
        # Module top level (scripts): the module body is one owner scope.
        # Top-level def/class STATEMENTS are excluded — walk_body_in_scope
        # only prunes scope nodes one level down, and those scopes were
        # already handled above.
        top = [s for s in module.tree.body
               if not isinstance(s, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.ClassDef))]
        findings.extend(_fn_findings(
            top, module.tree, module, None, "<module>", index, None))
        n_sites += sum(1 for s in walk_body_in_scope(top)
                       if _is_creation(s))
    return CheckerResult(findings=findings,
                         report={"spawn_sites": n_sites})
