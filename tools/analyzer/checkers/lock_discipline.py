"""Checker: no blocking work under a lock; one global acquisition order.

The serving/checkpoint planes are multi-threaded (engine dispatch +
completion + reload watcher + pool warmers + async checkpoint writer),
and two rules kept PR 3/4 honest:

1. **No blocking calls while holding a lock.** The engine's
   ``swap_params`` deliberately runs ``device_put`` OUTSIDE ``_lock``
   (the slow part), and the pool dispatches outside its lock; a
   ``block_until_ready``/``device_put``/file-IO/``queue.get``/``join``/
   collective under a lock serializes the data plane behind the slowest
   operation — or deadlocks outright (a collective under a lock the
   watchdog thread also wants is the no-concurrent-collectives rule's
   worst case).

2. **Consistent acquisition order.** The per-module lock graph (engine
   ``_lock``/``_staging_lock``, pool ``_lock``, profiling/compile-cache
   locks) must be acyclic: if one code path takes A then B and another
   takes B then A, the interleaving deadlocks. The checker reports the
   graph (nodes + nesting edges) in ``--format json`` so reviews can see
   the ordering at a glance.

Condition variables are exempt from rule 1 for their own ``wait``/
``notify`` — ``with cv: cv.wait()`` IS the pattern.

3. **Donation discipline** (ISSUE 16). The whole-program serving plane
   DONATES its staging buffer to the fused executable
   (``donate_argnums``): XLA owns that memory after dispatch. A donated
   buffer must therefore be ``retire()``d — counted and dropped — never
   ``release()``d back onto the staging free-list, where a future batch
   would stage into memory the program may already have overwritten (a
   use-after-free in staging clothing, racing under the very staging
   lock that is supposed to protect the pool). One function routing the
   SAME buffer expression to both ``retire()`` and ``release()`` is the
   signature of that bug and fires; the shipped engine keeps the two
   paths in separate dedicated helpers
   (``_retire_fused_staging``/``_release_staging``) so neither can
   reach the other's pool.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.analyzer._ast_util import (
    call_name,
    dotted_name,
    iter_functions,
    last_segment,
    walk_in_scope,
)
from tools.analyzer.core import CheckerResult, Finding, Module

CHECKER_ID = "lock-discipline"

_LOCK_CTORS = {"Lock", "RLock", "Condition"}

#: attribute-call names that block (matched on the last dotted segment).
_BLOCKING_ATTR_CALLS = {
    "block_until_ready": "a device sync",
    "device_put": "a host-to-device transfer",
    "urlopen": "network IO",
    "process_allgather": "a cross-host collective",
    "allgather_records": "a cross-host collective",
    "agree": "a cross-host collective",
    "_agree_phase_ok": "a cross-host collective",
    # The autoscaler's actuation (ISSUE 15): a pool resize builds and
    # AOT-warms a WHOLE replica layout — seconds of work. Under the
    # controller/stats/pool lock it stalls every /stats read and
    # dispatch for the build; the shipped shape snapshots state under
    # the lock and actuates after release.
    "resize": "a pool topology rebuild (build + AOT warm)",
    # The response-cache seam (ISSUE 19): cache payloads are built —
    # logits device-fetched, replies serialized — OUTSIDE the cache
    # lock; only the generation-checked insert runs under it
    # (snapshot-then-insert). A device_get under any lock stalls every
    # reader behind a D2H transfer.
    "device_get": "a device-to-host transfer",
}
_BLOCKING_BARE_CALLS = {
    "open": "file IO",
    "device_put": "a host-to-device transfer",
    "allgather_records": "a cross-host collective",
    "agree": "a cross-host collective",
}
_QUEUEISH = ("queue", "q")


def _lock_key(owner: str, attr: str) -> str:
    return f"{owner}.{attr}"


def _collect_locks(module: Module) -> Set[str]:
    """Lock objects: ``self.X = threading.Lock()`` (keyed by class) and
    module-level ``X = threading.Lock()`` (keyed by module)."""
    locks: Set[str] = set()
    for fn, _qual, classname in iter_functions(module.tree):
        for node in walk_in_scope(fn):
            if not isinstance(node, ast.Assign):
                continue
            if not (isinstance(node.value, ast.Call)
                    and last_segment(call_name(node.value)) in _LOCK_CTORS):
                continue
            for target in node.targets:
                if isinstance(target, ast.Attribute) and \
                        isinstance(target.value, ast.Name) and \
                        target.value.id == "self" and classname:
                    locks.add(_lock_key(classname, target.attr))
    for node in module.tree.body:
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                last_segment(call_name(node.value)) in _LOCK_CTORS:
            for target in node.targets:
                if isinstance(target, ast.Name):
                    locks.add(_lock_key("<module>", target.id))
    return locks


def _lock_for_expr(expr: ast.AST, classname: Optional[str],
                   locks: Set[str]) -> Optional[Tuple[str, str]]:
    """``(lock_key, source_text)`` when ``expr`` names a known lock."""
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and expr.value.id == "self" \
            and classname:
        key = _lock_key(classname, expr.attr)
        if key in locks:
            return key, f"self.{expr.attr}"
    if isinstance(expr, ast.Name):
        key = _lock_key("<module>", expr.id)
        if key in locks:
            return key, expr.id
    return None


def _is_queueish(name: str) -> bool:
    """Receiver names that plausibly hold a queue.Queue — ``.get``/``.put``
    are flagged only on these, because dict.get is everywhere."""
    low = name.lower().lstrip("_")
    return low in _QUEUEISH or "queue" in low


def _blocking_reason(node: ast.Call,
                     held_exprs: List[str]) -> Optional[str]:
    name = call_name(node)
    last = last_segment(name)
    if isinstance(node.func, ast.Name):
        # from-imports make every attr-style call a bare name
        # (``from runtime.supervision import _agree_phase_ok``), so the
        # bare lookup consults both tables.
        return _BLOCKING_BARE_CALLS.get(name) \
            or _BLOCKING_ATTR_CALLS.get(name)
    if not isinstance(node.func, ast.Attribute):
        return None
    receiver = dotted_name(node.func.value)
    if receiver in held_exprs and last in (
            "wait", "wait_for", "notify", "notify_all"):
        return None  # the condition-variable pattern on the held lock
    if last in _BLOCKING_ATTR_CALLS:
        return _BLOCKING_ATTR_CALLS[last]
    if name == "time.sleep":
        return "a sleep"
    if last == "join" and receiver is not None:
        # str.join false-positive guard: thread/process joins take no
        # positional iterable.
        if not node.args or "thread" in receiver.lower() \
                or "proc" in receiver.lower():
            return "a thread/process join"
    if last in ("get", "put") and receiver is not None \
            and _is_queueish(last_segment(receiver)):
        return "a queue handoff"
    return None


class _FnVisitor(ast.NodeVisitor):
    """Walk one function, tracking held locks across nested withs."""

    def __init__(self, module, qual, classname, locks, findings, edges):
        self.module = module
        self.qual = qual
        self.classname = classname
        self.locks = locks
        self.findings = findings
        self.edges = edges
        self.held: List[Tuple[str, str]] = []  # (key, source text)

    def _visit_scope_node(self, node) -> None:
        pass  # nested defs run later, under whatever locks THEY take

    visit_FunctionDef = _visit_scope_node
    visit_AsyncFunctionDef = _visit_scope_node
    visit_Lambda = _visit_scope_node
    visit_ClassDef = _visit_scope_node

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        # with-items enter left to right: each context expression runs
        # under only the locks acquired by the items BEFORE it, so visit
        # the expr first, then (if it names a lock) mark it held.
        for item in node.items:
            self.visit(item.context_expr)
            hit = _lock_for_expr(item.context_expr, self.classname,
                                 self.locks)
            if hit:
                if self.held:
                    self.edges.append(
                        (self.held[-1][0], hit[0], self.module.path,
                         node.lineno, self.qual))
                self.held.append(hit)
                acquired.append(hit)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()

    def visit_Call(self, node: ast.Call) -> None:
        if self.held:
            reason = _blocking_reason(node, [h[1] for h in self.held])
            if reason:
                key, text = self.held[-1]
                self.findings.append(Finding(
                    checker=CHECKER_ID, path=self.module.path,
                    line=node.lineno, col=node.col_offset,
                    symbol=self.qual,
                    message=(
                        f"{call_name(node) or 'call'}() — {reason} — "
                        f"executed while holding {text} ({key}): every "
                        f"thread contending for the lock now waits on "
                        f"{reason}, and a collective here can deadlock "
                        f"against the watchdog (no-concurrent-"
                        f"collectives rule)"),
                    hint=("move the blocking work outside the critical "
                          "section: snapshot state under the lock, "
                          "operate after release (the engine "
                          "swap_params idiom)"),
                ))
        self.generic_visit(node)


def _order_cycles(pairs) -> List[List[str]]:
    """Elementary cycles in the nesting-order graph, each reported once
    (deduped on the node set, anchored at its smallest lock). The
    2-cycle A->B/B->A is the common case, but a 3-lock ring deadlocks
    just as hard — lock graphs are a handful of nodes, so a plain DFS
    is plenty."""
    adj: Dict[str, List[str]] = {}
    for a, b in pairs:
        adj.setdefault(a, []).append(b)
    cycles: List[List[str]] = []
    seen_sets = set()
    for start in sorted(adj):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(adj.get(node, ()), reverse=True):
                if nxt == start:
                    key = frozenset(path)
                    if key not in seen_sets and start == min(path):
                        seen_sets.add(key)
                        cycles.append(path[:])
                elif nxt not in path:
                    stack.append((nxt, path + [nxt]))
    return cycles


def _donation_discipline(module: Module,
                         findings: List[Finding]) -> None:
    """Rule 3: the same buffer expression routed to BOTH ``retire()``
    and ``release()`` inside one function. Name leaves of the first
    argument are the identity (covers ``buf``, ``[(bucket, buf)]``,
    and a shared ``buffers`` list alike)."""
    for fn, qual, _classname in iter_functions(module.tree):
        routed: Dict[str, Dict[str, int]] = {"retire": {}, "release": {}}
        for node in walk_in_scope(fn):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in routed and node.args):
                continue
            for leaf in ast.walk(node.args[0]):
                if isinstance(leaf, ast.Name):
                    routed[node.func.attr].setdefault(leaf.id,
                                                      node.lineno)
        for name in sorted(set(routed["retire"]) & set(routed["release"])):
            line = max(routed["retire"][name], routed["release"][name])
            findings.append(Finding(
                checker=CHECKER_ID, path=module.path, line=line, col=0,
                symbol=qual,
                message=(
                    f"donation discipline: buffer {name!r} is routed to "
                    f"both retire() (line {routed['retire'][name]}) and "
                    f"release() (line {routed['release'][name]}) in one "
                    f"function — a DONATED buffer re-entering the "
                    f"free-list hands a future batch memory XLA already "
                    f"owns (use-after-free in staging clothing)"),
                hint=("keep the donated and pooled lifecycles in "
                      "separate dedicated helpers (the engine's "
                      "_retire_fused_staging/_release_staging split): "
                      "retired buffers are dropped, never re-listed"),
            ))


def run(modules: List[Module]) -> CheckerResult:
    findings: List[Finding] = []
    report: Dict[str, Dict] = {}
    for module in modules:
        _donation_discipline(module, findings)
        locks = _collect_locks(module)
        if not locks:
            continue
        edges: List[Tuple[str, str, str, int, str]] = []
        # Module-level statements first (init-time ``with _lock:`` in
        # scripts) — the visitor skips nested defs/classes, which
        # iter_functions then covers one by one.
        top = _FnVisitor(module, "<module>", None, locks, findings, edges)
        for stmt in module.tree.body:
            top.visit(stmt)
        for fn, qual, classname in iter_functions(module.tree):
            visitor = _FnVisitor(module, qual, classname, locks,
                                 findings, edges)
            for stmt in fn.body:
                visitor.visit(stmt)
        seen_pairs: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        for a, b, path, line, qual in edges:
            seen_pairs.setdefault((a, b), (path, line, qual))
        for cycle in _order_cycles(seen_pairs):
            # A 1-node cycle is a nested re-acquisition of the same
            # lock: the edge list is the single self-edge (A, A).
            cycle_edges = list(zip(cycle, cycle[1:] + [cycle[0]]))
            chain = " -> ".join(cycle + [cycle[0]])
            where = "; ".join(
                f"{a} -> {b} at "
                f"{seen_pairs[(a, b)][0]}:{seen_pairs[(a, b)][1]} "
                f"({seen_pairs[(a, b)][2]})"
                for a, b in cycle_edges)
            path, line, qual = seen_pairs[cycle_edges[0]]
            findings.append(Finding(
                checker=CHECKER_ID, path=path, line=line, col=0,
                symbol=qual,
                message=(
                    f"inconsistent lock order: acquisition cycle "
                    f"{chain} ({where}); some interleaving of these "
                    f"paths deadlocks"),
                hint="pick one global order and refactor the "
                     "minority path(s) to match it",
            ))
        report[module.path] = {
            "locks": sorted(locks),
            "order_edges": [
                {"outer": a, "inner": b, "at": f"{path}:{line}"}
                for (a, b), (path, line, _q) in sorted(seen_pairs.items())
            ],
        }
    return CheckerResult(findings=findings, report={"lock_graph": report})
