"""donated-reuse: a donated buffer is dead the moment the call returns.

The incident this encodes (docs/DESIGN.md §8): PR 7's fused train loop
donated the carry (``donate_argnums``) so XLA could update parameters
in place — which makes the *caller's* reference a dangling handle. The
shipped hazard was reading the old carry after the step (metrics
computed on donated params raise ``RuntimeError: invalid buffer`` at
best and alias freed memory at worst); the loop had to be written as
``state = step(state, batch)`` with nothing touching the old ``state``
afterwards.

Detection, per module (cross-module through the project index):

1. Donating bindings: ``f = jax.jit(fn, donate_argnums=(..))`` bound to
   a name or ``self`` attribute — or bound from a *factory* call whose
   resolved function returns such a jit (the ``make_step(...)`` idiom).
2. At each call through a donating binding, for every argument at a
   donated position that is a plain name/attribute chain:
   - straight-line reuse: the name is read again after the call before
     any rebinding — firing;
   - loop carry: the call sits in a ``for``/``while`` body that never
     rebinds the name — the next iteration re-donates a dead buffer —
     firing. (``state = step(state, ...)`` rebinding on the same
     statement is the blessed shape.)

The analysis is lexical (line-ordered within one function); dynamic
``donate_argnums`` values and donated positions passed as ``**kwargs``
are out of scope and never fire.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.analyzer._ast_util import (
    call_name,
    dotted_name,
    int_constants,
    iter_functions,
    last_segment,
    walk_body_in_scope,
)
from tools.analyzer.core import CheckerResult, Finding

CHECKER_ID = "donated-reuse"
NEEDS_INDEX = True


def _donated_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """Positions a jit-like call donates, None when it is not donating
    (or the positions are dynamic)."""
    if last_segment(call_name(call)) not in ("jit", "pjit"):
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            vals = int_constants(kw.value)
            return tuple(vals) if vals else None
    return None


def _factory_returns(index) -> Dict[str, Tuple[int, ...]]:
    """fq -> donated positions, for functions whose return value is a
    donating jit call (the make_step factory idiom)."""
    out: Dict[str, Tuple[int, ...]] = {}
    for fq, info in index.functions.items():
        for sub in walk_body_in_scope(info.node.body):
            if isinstance(sub, ast.Return) and \
                    isinstance(sub.value, ast.Call):
                pos = _donated_positions(sub.value)
                if pos:
                    out[fq] = pos
    return out


def _donating_bindings(fn: ast.AST, module, classname: Optional[str],
                       index, factories: Dict[str, Tuple[int, ...]]
                       ) -> Dict[str, Tuple[int, ...]]:
    """dotted binding name -> donated positions, for bindings made in
    ``fn`` (``step = jax.jit(...)`` / ``self._step = make_step(...)``)."""
    bindings: Dict[str, Tuple[int, ...]] = {}
    for sub in walk_body_in_scope(fn.body):
        if not (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                and isinstance(sub.value, ast.Call)):
            continue
        target = dotted_name(sub.targets[0])
        if not target:
            continue
        pos = _donated_positions(sub.value)
        if pos is None:
            for fq in index.resolve_call(sub.value, module, classname):
                if fq in factories:
                    pos = factories[fq]
                    break
        if pos:
            bindings[target] = pos
    return bindings


def _loads_of(node: ast.AST, dotted: str) -> List[ast.AST]:
    out: List[ast.AST] = []
    for sub in ast.walk(node):
        if dotted_name(sub) == dotted and \
                isinstance(getattr(sub, "ctx", None), ast.Load):
            out.append(sub)
    return out


def _rebind_lines(fn: ast.AST, dotted: str) -> Set[int]:
    lines: Set[int] = set()
    for sub in walk_body_in_scope(fn.body):
        targets: List[ast.expr] = []
        if isinstance(sub, ast.Assign):
            targets = sub.targets
        elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
            targets = [sub.target]
        elif isinstance(sub, ast.For):
            targets = [sub.target]
        for t in targets:
            for n in ast.walk(t):
                if dotted_name(n) == dotted and \
                        isinstance(getattr(n, "ctx", None), ast.Store):
                    lines.add(sub.lineno)
    return lines


def _enclosing_loop(call: ast.Call,
                    parents: Dict[int, ast.AST]) -> Optional[ast.AST]:
    cur: ast.AST = call
    while True:
        parent = parents.get(id(cur))
        if parent is None:
            return None
        if isinstance(parent, (ast.For, ast.While)):
            return parent
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
            return None
        cur = parent


def _parent_map(fn: ast.AST) -> Dict[int, ast.AST]:
    parents: Dict[int, ast.AST] = {}
    for node in ast.walk(fn):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    return parents


def _check_call(call: ast.Call, positions: Tuple[int, ...], fn: ast.AST,
                module, symbol: str,
                parents: Dict[int, ast.AST]) -> List[Finding]:
    findings: List[Finding] = []
    for pos in positions:
        if pos >= len(call.args):
            continue
        arg = dotted_name(call.args[pos])
        if not arg:
            continue
        rebinds = _rebind_lines(fn, arg)
        call_end = getattr(call, "end_lineno", call.lineno)
        next_rebind = min((ln for ln in rebinds if ln >= call.lineno),
                          default=None)
        # straight-line: a read after the call, before any rebinding
        for load in sorted(_loads_of(fn, arg), key=lambda n: n.lineno):
            if load.lineno <= call_end:
                continue
            if next_rebind is not None and load.lineno > next_rebind:
                break
            findings.append(Finding(
                checker=CHECKER_ID, path=module.path,
                line=load.lineno, col=load.col_offset, symbol=symbol,
                message=f"{arg!r} was donated at line {call.lineno} "
                        f"(donate_argnums position {pos}) and is read "
                        f"again here — the buffer no longer exists "
                        f"(the PR 7 carry hazard)",
                hint="use the call's RESULT; a donated argument is "
                     "consumed by the callee"))
            break
        # loop carry: donated every iteration but never rebound
        loop = _enclosing_loop(call, parents)
        if loop is not None:
            loop_end = getattr(loop, "end_lineno", loop.lineno)
            rebound_in_loop = any(
                loop.lineno <= ln <= loop_end for ln in rebinds)
            if not rebound_in_loop:
                findings.append(Finding(
                    checker=CHECKER_ID, path=module.path,
                    line=call.lineno, col=call.col_offset,
                    symbol=symbol,
                    message=f"{arg!r} is donated every loop iteration "
                            f"but never rebound — the second iteration "
                            f"donates a buffer the first already "
                            f"consumed",
                    hint="carry the result: `x = fn(x, ...)`"))
    return findings


def run(modules, index) -> CheckerResult:
    findings: List[Finding] = []
    factories = _factory_returns(index)
    n_bindings = 0
    for module in modules:
        for fn, qual, classname in iter_functions(module.tree):
            bindings = _donating_bindings(fn, module, classname, index,
                                          factories)
            # bindings made on self in __init__ are visible to every
            # method of the class
            n_bindings += len(bindings)
            if not bindings:
                continue
            parents = _parent_map(fn)
            for sub in walk_body_in_scope(fn.body):
                if isinstance(sub, ast.Call):
                    name = dotted_name(sub.func)
                    if name in bindings:
                        findings.extend(_check_call(
                            sub, bindings[name], fn, module, qual,
                            parents))
    # self-attribute bindings cross method boundaries: collect per class
    for module in modules:
        class_bindings: Dict[Optional[str], Dict[str, Tuple[int, ...]]] \
            = {}
        for fn, qual, classname in iter_functions(module.tree):
            if classname is None:
                continue
            b = _donating_bindings(fn, module, classname, index,
                                   factories)
            selfb = {k: v for k, v in b.items() if k.startswith("self.")}
            if selfb:
                class_bindings.setdefault(classname, {}).update(selfb)
        if not class_bindings:
            continue
        for fn, qual, classname in iter_functions(module.tree):
            bindings = class_bindings.get(classname)
            if not bindings:
                continue
            local = _donating_bindings(fn, module, classname, index,
                                       factories)
            parents = _parent_map(fn)
            for sub in walk_body_in_scope(fn.body):
                if isinstance(sub, ast.Call):
                    name = dotted_name(sub.func)
                    if name in bindings and name not in local:
                        findings.extend(_check_call(
                            sub, bindings[name], fn, module, qual,
                            parents))
    return CheckerResult(findings=findings,
                         report={"donating_bindings": n_bindings})
