"""Checker: AOT executables get arrays, jit wrappers declare their statics.

Two recompile/arg-mismatch hazards the compile subsystem (PR 1) and the
serving engine (PR 3/4) turned into asserted invariants:

1. **Raw scalars into compiled executables.** A ``.lower().compile()``
   / ``precompile(...)`` product is an ``XlaExecutable`` with a FIXED
   argument spec. Passing a raw Python scalar where the spec holds an
   array either raises an argument-mismatch at serve time or — through a
   jit fallback wrapper — silently keys a fresh compile. Call sites of
   names bound to compiled executables must pass arrays (or variables),
   never bare numeric literals.

2. **jit without static declarations.** ``jax.jit(fn)`` where ``fn``
   takes hashable config parameters (bool/str defaults — flags like
   ``interpret=False``) traces those as array arguments; each distinct
   value then either fails hashing or recompiles per call. The jit site
   must declare them via ``static_argnums``/``static_argnames`` (the
   ops/pallas/adam.py idiom).
"""

from __future__ import annotations

import ast
from typing import List, Set

from tools.analyzer._ast_util import (
    call_name,
    defs_by_name,
    dotted_name,
    iter_functions,
    last_segment,
    walk_in_scope,
)
from tools.analyzer.core import CheckerResult, Finding, Module

CHECKER_ID = "recompile-hazard"


def _is_compiled_producer(value: ast.AST) -> bool:
    """True for ``precompile(...)`` and ``<x>.lower(...).compile()``."""
    if not isinstance(value, ast.Call):
        return False
    if last_segment(call_name(value)) == "precompile":
        return True
    if isinstance(value.func, ast.Attribute) and \
            value.func.attr == "compile":
        inner = value.func.value
        if isinstance(inner, ast.Call) and \
                isinstance(inner.func, ast.Attribute) and \
                inner.func.attr == "lower":
            return True
    return False


def _scalar_positions(call: ast.Call) -> List[int]:
    hits = []
    for i, arg in enumerate(call.args):
        node = arg
        if isinstance(node, ast.UnaryOp) and \
                isinstance(node.op, (ast.USub, ast.UAdd)):
            node = node.operand
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, (int, float)) and \
                not isinstance(node.value, bool):
            hits.append(i)
    return hits


def _check_compiled_calls(module: Module, findings: List[Finding]) -> None:
    """Rule 1, per scope: names (and self-attributes) assigned a compiled
    executable, then called with numeric literals."""
    scopes = [(module.tree, "<module>")] + [
        (fn, qual) for fn, qual, _cls in iter_functions(module.tree)]
    # self-attribute assignments are visible across a class's methods.
    attr_names: Set[str] = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Assign) and _is_compiled_producer(node.value):
            for target in node.targets:
                if isinstance(target, ast.Attribute) and \
                        isinstance(target.value, ast.Name) and \
                        target.value.id == "self":
                    attr_names.add(target.attr)
    for scope, qual in scopes:
        local: Set[str] = set()
        for node in walk_in_scope(scope):
            if isinstance(node, ast.Assign) and \
                    _is_compiled_producer(node.value):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        local.add(target.id)
        for node in walk_in_scope(scope):
            if not isinstance(node, ast.Call):
                continue
            is_exec = (
                isinstance(node.func, ast.Name) and node.func.id in local
            ) or (
                isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"
                and node.func.attr in attr_names
            )
            if not is_exec:
                continue
            for pos in _scalar_positions(node):
                findings.append(Finding(
                    checker=CHECKER_ID, path=module.path,
                    line=node.lineno, col=node.col_offset, symbol=qual,
                    message=(
                        f"raw Python scalar at argument {pos} of an "
                        f"AOT-compiled executable call: the compiled "
                        f"program's spec holds committed arrays, so "
                        f"this either fails the argument check or "
                        f"re-keys a compile through a fallback wrapper"),
                    hint=("wrap the literal (jnp.asarray/np.asarray) "
                          "with the dtype the spec was lowered with"),
                ))


def _jit_call_static_kwargs(call: ast.Call) -> bool:
    return any(kw.arg in ("static_argnums", "static_argnames")
               for kw in call.keywords)


def _config_defaults(fn: ast.AST) -> List[str]:
    """Parameters whose default is a bool/str constant — hashable config
    the jit site must declare static."""
    args = fn.args
    named = args.posonlyargs + args.args
    out: List[str] = []
    for param, default in zip(named[len(named) - len(args.defaults):],
                              args.defaults):
        if isinstance(default, ast.Constant) and \
                isinstance(default.value, (bool, str)):
            out.append(param.arg)
    for param, default in zip(args.kwonlyargs, args.kw_defaults):
        if isinstance(default, ast.Constant) and \
                isinstance(default.value, (bool, str)):
            out.append(param.arg)
    return out


def _check_jit_statics(module: Module, findings: List[Finding]) -> None:
    defs = defs_by_name(module.tree)
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        if last_segment(call_name(node)) != "jit":
            continue
        if not node.args or not isinstance(node.args[0], ast.Name):
            continue  # partials / attributes: bindings untrackable
        if _jit_call_static_kwargs(node):
            continue
        for fn in defs.get(node.args[0].id, []):
            config = _config_defaults(fn)
            if config:
                findings.append(Finding(
                    checker=CHECKER_ID, path=module.path,
                    line=node.lineno, col=node.col_offset,
                    symbol=node.args[0].id,
                    message=(
                        f"jit({node.args[0].id}) without "
                        f"static_argnums/static_argnames, but "
                        f"{node.args[0].id}() takes hashable config "
                        f"parameter(s) {config}: each distinct value "
                        f"traces as an array arg and recompiles (or "
                        f"fails hashing) per call"),
                    hint=("declare them static at the jit site, or bind "
                          "them with functools.partial before jitting "
                          "(the train/steps.py idiom)"),
                ))
                break
    # Decorator form: @jit directly on a def with config defaults.
    for fn, qual, _cls in iter_functions(module.tree):
        for dec in fn.decorator_list:
            if not (isinstance(dec, (ast.Name, ast.Attribute))
                    and last_segment(dotted_name(dec)) == "jit"):
                continue
            config = _config_defaults(fn)
            if config:
                findings.append(Finding(
                    checker=CHECKER_ID, path=module.path,
                    line=dec.lineno, col=dec.col_offset, symbol=qual,
                    message=(
                        f"@jit on {fn.name}() which takes hashable "
                        f"config parameter(s) {config} with no static "
                        f"declaration: per-value retrace/recompile"),
                    hint=("use @functools.partial(jax.jit, "
                          "static_argnames=(...)) — the "
                          "ops/pallas/adam.py idiom"),
                ))


def run(modules: List[Module]) -> CheckerResult:
    findings: List[Finding] = []
    for module in modules:
        _check_compiled_calls(module, findings)
        _check_jit_statics(module, findings)
    return CheckerResult(findings=findings)
