"""Checker: traced/lowered functions must be pure and AOT-stable.

Serving and the AOT precompile path assert ZERO steady-state recompiles
(serve/engine.py, bench.py) and the trainer calls compiled executables
directly — which only holds if the traced program is a pure function of
its array arguments. Host side effects inside a traced body either
silently run once at trace time (print/logging/time/random: debugging
lies, nondeterminism baked into the program) or force a host sync /
retrace (``.item()``, ``float()``, ``np.asarray`` on a tracer).

Discovery: a function is *traced* when it is

- decorated with ``jit``/``shard_map``/``pallas_call`` (bare, dotted, or
  via ``functools.partial(jax.jit, ...)``),
- passed by name to a ``jit(...)``/``shard_map(...)``/``pallas_call(...)``
  call in the same module (the factory idiom train/steps.py uses), or
- called by name from an already-traced function in the same module
  (call-graph walk; nested defs of a traced function are traced too).

The walk is module-local and name-based by design: cross-module calls
(``cross_entropy`` from ops/loss.py) are each module's own business —
their traced roots are discovered when THAT module is analyzed.

``static_argnames``/``static_argnums`` declared at the jit site exempt
those parameters from the tracer-leak rules (``float(static_cfg)`` is
resolved at trace time, which is the point of declaring it static).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.analyzer._ast_util import (
    call_name,
    defs_by_name,
    dotted_name,
    function_param_names,
    head_segment,
    int_constants,
    last_segment,
    str_constants,
    walk_in_scope,
)
from tools.analyzer.core import CheckerResult, Finding, Module

CHECKER_ID = "trace-purity"

TRACE_ENTRY_POINTS = {"jit", "shard_map", "pallas_call"}
_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "critical",
                "exception", "log"}
_TIME_FUNCS = {"time", "sleep", "monotonic", "perf_counter",
               "process_time", "time_ns", "monotonic_ns",
               "perf_counter_ns"}
#: numpy-module aliases whose ``asarray`` materializes on the host.
_HOST_NUMPY = {"np", "numpy"}


def _static_names_from_call(call: ast.Call, fn: ast.AST) -> Set[str]:
    """Parameters declared static at a jit site (names or argnums)."""
    static: Set[str] = set()
    params = function_param_names(fn) if fn is not None else []
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            static.update(str_constants(kw.value))
        elif kw.arg == "static_argnums":
            for idx in int_constants(kw.value):
                if 0 <= idx < len(params):
                    static.add(params[idx])
    return static


def _decorator_trace_info(fn: ast.AST) -> Optional[Set[str]]:
    """None if the decorators don't trace ``fn``; else the set of static
    parameter names the tracing decorator declares."""
    for dec in fn.decorator_list:
        if isinstance(dec, (ast.Name, ast.Attribute)):
            if last_segment(dotted_name(dec)) in TRACE_ENTRY_POINTS:
                return set()
        elif isinstance(dec, ast.Call):
            name = last_segment(call_name(dec))
            if name in TRACE_ENTRY_POINTS:
                return _static_names_from_call(dec, fn)
            if name == "partial" and dec.args:
                inner = dec.args[0]
                if last_segment(dotted_name(inner)) in TRACE_ENTRY_POINTS:
                    return _static_names_from_call(dec, fn)
    return None


def _find_roots(tree: ast.Module, defs) -> List[Tuple[ast.AST, Set[str]]]:
    roots: List[Tuple[ast.AST, Set[str]]] = []
    for name, nodes in defs.items():
        for fn in nodes:
            static = _decorator_trace_info(fn)
            if static is not None:
                roots.append((fn, static))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if last_segment(call_name(node)) not in TRACE_ENTRY_POINTS:
            continue
        if not node.args or not isinstance(node.args[0], ast.Name):
            continue  # partials/attributes: statics untrackable, skip
        target = node.args[0].id
        for fn in defs.get(target, []):
            roots.append((fn, _static_names_from_call(node, fn)))
    return roots


def _called_local_names(fn: ast.AST) -> Set[str]:
    names: Set[str] = set()
    for node in ast.walk(fn):  # nested defs included: they share tracing
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            names.add(node.func.id)
    return names


def _traced_closure(tree, defs, roots) -> Dict[int, Tuple[ast.AST, Set[str]]]:
    """Transitive closure over same-module calls; id(fn) -> (fn, static)."""
    traced: Dict[int, Tuple[ast.AST, Set[str]]] = {}
    work = list(roots)
    while work:
        fn, static = work.pop()
        if id(fn) in traced:
            continue
        traced[id(fn)] = (fn, static)
        for callee in _called_local_names(fn):
            for target in defs.get(callee, []):
                if id(target) not in traced:
                    work.append((target, set()))
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn and id(node) not in traced:
                work.append((node, set()))
    return traced


def _check_traced_fn(module: Module, fn, static: Set[str],
                     findings: List[Finding]) -> None:
    tracer_params = {p for p in function_param_names(fn)
                     if p not in static and p != "self"}

    def report(node, message, hint):
        findings.append(Finding(
            checker=CHECKER_ID, path=module.path, line=node.lineno,
            col=node.col_offset, symbol=fn.name, message=message,
            hint=hint))

    for node in walk_in_scope(fn):  # nested defs are their own entries
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            kind = "global" if isinstance(node, ast.Global) else "nonlocal"
            report(node,
                   f"traced function declares `{kind} "
                   f"{', '.join(node.names)}`: mutating enclosing state "
                   f"under trace runs once at trace time and never again "
                   f"in the compiled program",
                   "return the value instead; traced programs must be "
                   "pure functions of their arguments")
            continue
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        last = last_segment(name)
        head = head_segment(name)
        if isinstance(node.func, ast.Name) and node.func.id == "print":
            report(node,
                   "print() inside a traced function executes once at "
                   "trace time, not per step — and a callback-based "
                   "print would block AOT stability",
                   "drop it, or use jax.debug.print for traced values")
        elif head == "logging" or (head in {"logger", "log"}
                                   and last in _LOG_METHODS):
            report(node,
                   f"{name}() inside a traced function fires at trace "
                   f"time only; per-step logging belongs on the host "
                   f"side of the step boundary",
                   "log outside the traced program (trainer/engine own "
                   "the host loop)")
        elif head == "time" and last in _TIME_FUNCS:
            report(node,
                   f"{name}() under trace bakes the trace-time value "
                   f"into the compiled program (and sleep would stall "
                   f"compilation, not execution)",
                   "measure on the host around the compiled call")
        elif head == "random":
            report(node,
                   f"Python {name}() under trace freezes one sample "
                   f"into the program — every execution reuses it",
                   "use jax.random with an explicit key argument")
        elif last == "item" and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in tracer_params \
                and not node.args:
            report(node,
                   f"`.item()` on tracer-typed argument "
                   f"{node.func.value.id!r}: forces a host sync under "
                   f"trace (ConcretizationTypeError at best, a hidden "
                   f"device round-trip at worst)",
                   "keep the value on device; reduce with jnp and "
                   "fetch after the compiled call returns")
        elif isinstance(node.func, ast.Name) and node.func.id == "float" \
                and len(node.args) == 1 \
                and isinstance(node.args[0], ast.Name) \
                and node.args[0].id in tracer_params:
            report(node,
                   f"float() on tracer-typed argument "
                   f"{node.args[0].id!r} concretizes under trace",
                   "use jnp.asarray(..., jnp.float32) to stay abstract, "
                   "or declare the parameter static at the jit site")
        elif head in _HOST_NUMPY and last == "asarray" and node.args \
                and isinstance(node.args[0], ast.Name) \
                and node.args[0].id in tracer_params:
            report(node,
                   f"{name}() on tracer-typed argument "
                   f"{node.args[0].id!r} materializes the tracer on the "
                   f"host (concretization error / silent device sync)",
                   "use jnp.asarray inside traced code; np.asarray "
                   "belongs on the host side")


def run(modules: List[Module]) -> CheckerResult:
    findings: List[Finding] = []
    n_traced = 0
    for module in modules:
        defs = defs_by_name(module.tree)
        roots = _find_roots(module.tree, defs)
        traced = _traced_closure(module.tree, defs, roots)
        n_traced += len(traced)
        for fn, static in traced.values():
            _check_traced_fn(module, fn, static, findings)
    return CheckerResult(findings=findings,
                         report={"traced_functions": n_traced})
