"""short-read: verify Content-Length before trusting an HTTP body.

The incident this encodes (docs/DESIGN.md §8): PR 19's delta-fetch path
(``distrib/fetch.py``) read chunk bodies piecewise with ``read(n)`` —
which reports a torn connection as a plain short body, NOT as
``http.client.IncompleteRead`` (only the unsized ``read()`` raises that)
— and handed truncated bytes to the chunk-hash verifier. The fix
compares received length against the ``Content-Length`` header and
treats a mismatch as a transport error (retryable) instead of corrupt
data (fatal). The same hole existed in the router's backend proxy
(``serve/router.py http_exchange``) and the dataset fetch
(``data/download.py``).

Mechanically: inside one function, a *receiver* is a name bound from
``urlopen(...)`` or ``conn.getresponse(...)`` (assignment or
``with ... as r``). A ``receiver.read(...)`` call fires unless:

- the function *validates length*: some name tainted by the string
  ``"Content-Length"`` (header lookup, propagated through assignments)
  participates in a comparison — the received-vs-expected check, or
- the read's result is fed straight to ``json.loads(...)`` — a torn
  JSON body fails the parse, so the decode IS the integrity check, or
- the result is discarded (a bare expression statement): draining a
  keep-alive socket does not *use* the bytes.

Receivers passed in from a caller are that caller's responsibility —
the checker is owner-scoped, like thread-lifecycle.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from tools.analyzer._ast_util import (
    call_name,
    dotted_name,
    iter_functions,
    last_segment,
    walk_body_in_scope,
)
from tools.analyzer.core import CheckerResult, Finding

CHECKER_ID = "short-read"

_RECEIVER_CALLS = {"urlopen", "getresponse"}
_HEADER_NEEDLE = "content-length"


def _mentions_content_length(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str) \
                and sub.value.lower() == _HEADER_NEEDLE:
            return True
    return False


def _assigned_names(target: ast.expr) -> List[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[str] = []
        for e in target.elts:
            out.extend(_assigned_names(e))
        return out
    return []


def _collect_receivers(fn: ast.AST) -> Set[str]:
    receivers: Set[str] = set()
    for sub in walk_body_in_scope(fn.body):
        if isinstance(sub, ast.Assign) and len(sub.targets) == 1 and \
                isinstance(sub.targets[0], ast.Name) and \
                isinstance(sub.value, ast.Call) and \
                last_segment(call_name(sub.value)) in _RECEIVER_CALLS:
            receivers.add(sub.targets[0].id)
        elif isinstance(sub, ast.withitem) and \
                isinstance(sub.context_expr, ast.Call) and \
                last_segment(call_name(sub.context_expr)) in \
                _RECEIVER_CALLS and \
                isinstance(sub.optional_vars, ast.Name):
            receivers.add(sub.optional_vars.id)
    return receivers


def _validates_length(fn: ast.AST) -> bool:
    """Taint names from Content-Length lookups through assignments; a
    comparison touching any tainted name is the received-length check."""
    tainted: Set[str] = set()
    changed = True
    rounds = 0
    while changed and rounds < 8:
        changed = False
        rounds += 1
        for sub in walk_body_in_scope(fn.body):
            if not isinstance(sub, ast.Assign):
                continue
            rhs_tainted = _mentions_content_length(sub.value) or any(
                isinstance(n, ast.Name) and n.id in tainted
                for n in ast.walk(sub.value))
            if not rhs_tainted:
                continue
            for t in sub.targets:
                for name in _assigned_names(t):
                    if name not in tainted:
                        tainted.add(name)
                        changed = True
    if not tainted:
        return False
    for sub in walk_body_in_scope(fn.body):
        if isinstance(sub, ast.Compare):
            for n in ast.walk(sub):
                if isinstance(n, ast.Name) and n.id in tainted:
                    return True
    return False


def _parent_map(fn: ast.AST) -> Dict[int, ast.AST]:
    parents: Dict[int, ast.AST] = {}
    for node in ast.walk(fn):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    return parents


def _is_json_decoded(read_call: ast.Call,
                     parents: Dict[int, ast.AST]) -> bool:
    cur: ast.AST = read_call
    while True:
        parent = parents.get(id(cur))
        if parent is None or isinstance(parent, ast.stmt):
            return False
        if isinstance(parent, ast.Call) and (
                cur in parent.args or
                any(kw.value is cur for kw in parent.keywords)):
            if last_segment(call_name(parent)) in ("loads", "load"):
                return True
            return False  # handed to some other consumer: its bytes now
        cur = parent


def _is_discarded(read_call: ast.Call,
                  parents: Dict[int, ast.AST]) -> bool:
    parent = parents.get(id(read_call))
    return isinstance(parent, ast.Expr) and parent.value is read_call


def _fn_findings(fn: ast.AST, module, symbol: str) -> List[Finding]:
    receivers = _collect_receivers(fn)
    if not receivers:
        return []
    if _validates_length(fn):
        return []
    parents = _parent_map(fn)
    findings: List[Finding] = []
    for sub in walk_body_in_scope(fn.body):
        if not (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "read"):
            continue
        base = dotted_name(sub.func.value)
        if base not in receivers:
            continue
        if _is_json_decoded(sub, parents) or _is_discarded(sub, parents):
            continue
        findings.append(Finding(
            checker=CHECKER_ID, path=module.path, line=sub.lineno,
            col=sub.col_offset, symbol=symbol,
            message="HTTP body read without comparing received length "
                    "to Content-Length — a torn connection hands "
                    "truncated bytes downstream (the PR 19 "
                    "distrib/fetch.py torn-chunk shape)",
            hint="read the Content-Length header and verify the "
                 "received byte count against it (a mismatch is a "
                 "retryable transport error, not data)"))
    return findings


def run(modules) -> CheckerResult:
    findings: List[Finding] = []
    n_receivers = 0
    for module in modules:
        for fn, qual, _classname in iter_functions(module.tree):
            n_receivers += len(_collect_receivers(fn))
            findings.extend(_fn_findings(fn, module, qual))
    return CheckerResult(findings=findings,
                         report={"http_receivers": n_receivers})
