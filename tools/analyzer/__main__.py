"""CLI for tpumnist-lint: ``python -m tools.analyzer [options] [paths]``.

Exit codes: 0 clean (baselined findings allowed), 1 findings / stale or
invalid baseline entries, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

# Standalone invocation from anywhere: the repo root (two levels up) must
# be importable for the absolute ``tools.analyzer`` imports.
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.analyzer import (  # noqa: E402
    checker_registry,
    default_cache_path,
    render_sarif,
    render_text,
    run_analysis,
)

#: What the tier-1 gate analyzes when no paths are given (tools/lint.sh
#: and tests/test_analyzer_gate.py pin the same set).
DEFAULT_PATHS = ("pytorch_distributed_mnist_tpu", "tools", "bench.py")


def _git_changed_files():
    """Modified + untracked .py files from git, repo-root relative
    absolute paths; None when git is unavailable (not a checkout)."""
    try:
        out = subprocess.run(
            ["git", "status", "--porcelain"], cwd=_REPO,
            capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0:
        return None
    files = []
    for line in out.stdout.splitlines():
        if len(line) < 4:
            continue
        path = line[3:].strip()
        if " -> " in path:  # rename: the new side is what exists now
            path = path.split(" -> ", 1)[1]
        path = path.strip('"')
        if path.endswith(".py"):
            files.append(os.path.join(_REPO, path))
    return files


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tools.analyzer",
        description="tpumnist-lint: AST invariant checker (collective "
                    "symmetry, agreement except-breadth, trace purity, "
                    "recompile hazards, lock discipline, registry "
                    "drift, thread lifecycle, handler discipline, "
                    "generation ordering, short reads, donated reuse)",
    )
    p.add_argument("paths", nargs="*", default=None,
                   help=f"files/directories to analyze (default: "
                        f"{' '.join(DEFAULT_PATHS)}, resolved from the "
                        f"repo root)")
    p.add_argument("--format", choices=("text", "json", "sarif"),
                   default="text")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="baseline file of triaged-accepted findings "
                        "(default: tools/analyzer/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline: report every finding")
    p.add_argument("--checkers", default=None, metavar="ID[,ID...]",
                   help="run only these checkers")
    p.add_argument("--list-checkers", action="store_true")
    p.add_argument("--no-cache", action="store_true",
                   help="skip the per-file content-hash findings cache "
                        "(tools/analyzer/.cache.json)")
    p.add_argument("--changed", action="store_true",
                   help="analyze only files git reports as changed, "
                        "plus their reverse dependencies from the "
                        "cross-module import graph")
    args = p.parse_args(argv)

    if args.list_checkers:
        for cid, mod in checker_registry().items():
            doc = (mod.__doc__ or "").strip().splitlines()
            print(f"{cid}\t{doc[0] if doc else ''}")
        return 0

    paths = args.paths or [
        p if os.path.isabs(p) else os.path.join(_REPO, p)
        for p in DEFAULT_PATHS
    ]
    checkers = None
    if args.checkers:
        checkers = [c.strip() for c in args.checkers.split(",") if c.strip()]
    if args.no_baseline:
        baseline = None
    elif args.baseline is not None:
        baseline = args.baseline
    else:
        baseline = "default"

    changed = None
    if args.changed:
        changed = _git_changed_files()
        if changed is None:
            print("warning: --changed needs a git checkout; analyzing "
                  "everything", file=sys.stderr)

    cache = None
    if not args.no_cache and changed is None:
        cache = default_cache_path()

    try:
        result = run_analysis(paths, checkers=checkers, baseline=baseline,
                              cache=cache, changed=changed)
    except ValueError as exc:  # unknown checker ids
        print(f"error: {exc}", file=sys.stderr)
        return 2

    if args.format == "json":
        print(json.dumps(result.to_dict(), indent=2, sort_keys=True))
    elif args.format == "sarif":
        print(render_sarif(result))
    else:
        print(render_text(result))
    if any(f.checker == "usage" for f in result.findings):
        return 2  # misconfigured invocation, not a lint failure
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
