"""Shared AST helpers for the tpumnist-lint checkers.

Pure stdlib ``ast`` — no imports of the analyzed code. Everything here is
syntactic: dotted-name rendering, scope walks that respect function
boundaries, and small predicates the checkers share so their notion of
"a call to X" cannot drift from one another.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Sequence, Tuple

#: Node types that open a new runtime scope — traversals that reason about
#: "code executed here" must not descend into these.
SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)


def dotted_name(node: ast.AST) -> Optional[str]:
    """Render ``a.b.c`` for a Name/Attribute chain; None for anything
    dynamic (subscripts, call results) — callers treat None as 'unknown',
    never as a match."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def call_name(node: ast.Call) -> Optional[str]:
    return dotted_name(node.func)


def last_segment(name: Optional[str]) -> str:
    return name.rsplit(".", 1)[-1] if name else ""


def head_segment(name: Optional[str]) -> str:
    return name.split(".", 1)[0] if name else ""


def walk_in_scope(node: ast.AST) -> Iterator[ast.AST]:
    """Yield ``node``'s subtree WITHOUT entering nested function/class
    scopes: the statements that actually execute when this scope runs.
    ``node`` itself is yielded (unless it is a scope node being entered
    from outside — callers pass a function's *body* items, not the def)."""
    stack: List[ast.AST] = [node]
    while stack:
        cur = stack.pop()
        yield cur
        for child in ast.iter_child_nodes(cur):
            if isinstance(child, SCOPE_NODES):
                continue
            stack.append(child)


def walk_body_in_scope(body: Sequence[ast.stmt]) -> Iterator[ast.AST]:
    for stmt in body:
        yield from walk_in_scope(stmt)


def iter_functions(
    tree: ast.AST,
) -> Iterator[Tuple[ast.AST, str, Optional[str]]]:
    """Yield ``(funcnode, qualname, classname)`` for every function in the
    module, nested ones included. ``qualname`` is dotted through the
    enclosing defs/classes; ``classname`` is the nearest enclosing class
    (None at module level) — the lock checker keys lock objects by it."""

    def visit(node: ast.AST, prefix: str, classname: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                yield child, qual, classname
                yield from visit(child, qual, classname)
            elif isinstance(child, ast.ClassDef):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                yield from visit(child, qual, child.name)
            else:
                yield from visit(child, prefix, classname)

    yield from visit(tree, "", None)


def defs_by_name(tree: ast.AST) -> dict:
    """``{name: [def nodes]}`` over the whole module, nested defs included
    — the shared "resolve a bare callee name" index (trace-purity's call
    graph and recompile-hazard's jit-site lookup must agree on it)."""
    defs: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    return defs


def function_param_names(fn: ast.AST) -> List[str]:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return names


def handler_type_names(handler: ast.ExceptHandler) -> List[str]:
    """The dotted names an ``except`` clause catches; ``[]`` for a bare
    ``except:``. Unresolvable entries (dynamic expressions) render as
    ``"<dynamic>"`` so breadth checks stay conservative."""
    t = handler.type
    if t is None:
        return []
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    return [dotted_name(e) or "<dynamic>" for e in elts]


def contains_call_to(node: ast.AST, last_segments: set) -> bool:
    """True when ``node``'s in-scope subtree calls any function whose last
    dotted segment is in ``last_segments``."""
    for sub in walk_in_scope(node):
        if isinstance(sub, ast.Call) and \
                last_segment(call_name(sub)) in last_segments:
            return True
    return False


def body_contains_any_call(body: Sequence[ast.stmt]) -> bool:
    for sub in walk_body_in_scope(body):
        if isinstance(sub, ast.Call):
            return True
    return False


def body_contains_raise(body: Sequence[ast.stmt]) -> bool:
    for sub in walk_body_in_scope(body):
        if isinstance(sub, ast.Raise):
            return True
    return False


def str_constants(node: ast.AST) -> List[str]:
    """String literals inside a tuple/list/single-constant expression."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
        return out
    return []


def int_constants(node: ast.AST) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int) \
                    and not isinstance(e.value, bool):
                out.append(e.value)
        return out
    return []


# ---------------------------------------------------------------------------
# Project-wide def/call index (analyzer v2)
# ---------------------------------------------------------------------------


def module_name(path: str) -> str:
    """Dotted module name for a repo-relative posix path.
    ``pkg/serve/engine.py`` -> ``pkg.serve.engine``; ``pkg/__init__.py``
    -> ``pkg``; ``bench.py`` -> ``bench``."""
    p = path.replace("\\", "/")
    if p.endswith(".py"):
        p = p[: -len(".py")]
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.strip("/").replace("/", ".")


class FunctionInfo:
    """One indexed function/method: its AST, owner module, and names."""

    __slots__ = ("node", "module", "modname", "qualname", "name",
                 "classname")

    def __init__(self, node, module, modname, qualname, name, classname):
        self.node = node
        self.module = module
        self.modname = modname
        self.qualname = qualname  # "<modname>.<Class>.<method>"
        self.name = name
        self.classname = classname


class ProjectIndex:
    """ONE def/call index over every analyzed module.

    The PR 5 checkers resolved calls per-module (lock-discipline's
    ``self._attr = fn`` factory trick, trace-purity's bare-name def map);
    the incidents of PRs 4/10/19 broke across module seams those maps
    cannot see (engine -> pool -> watcher, server handler -> helper).
    This index is the whole-program version: qualified names for every
    def, ``from x import y`` / ``import x.y as z`` resolution, the same
    ``self._attr = fn`` factory-assignment resolution lock-discipline
    does locally, a call graph over all of it, and reachability queries
    with memoization. It is still purely syntactic — nothing under
    analysis is ever imported.

    Resolution is deliberately *over*-approximate at dynamic seams: an
    attribute call we cannot resolve exactly (``replica.engine.foo()``)
    falls back to matching every project def with that bare name, capped
    at ``_FALLBACK_CAP`` candidates so generic names (``get``, ``read``)
    do not connect everything to everything. More edges means MORE
    reachability, which for every v2 checker means FEWER findings — the
    fallback can only ever make the analyzer quieter, never noisier.
    """

    _FALLBACK_CAP = 6

    def __init__(self, modules) -> None:
        self.modules = list(modules)
        self.functions: dict = {}     # qualname -> FunctionInfo
        self.by_name: dict = {}       # bare name -> [qualname]
        self._modnames: dict = {}     # dotted module name -> Module
        self._imports: dict = {}      # module path -> {alias: dotted target}
        self._methods: dict = {}      # (modname, class) -> {method: qual}
        self._factories: dict = {}    # (modname, class) -> {attr: dotted}
        self._class_nodes: dict = {}  # (modname, class) -> ast.ClassDef
        self.import_graph: dict = {}  # module path -> set(module path)
        self._fq_by_node: dict = {}   # id(funcnode) -> qualname
        self._edges: dict = {}        # qualname -> frozenset(qualname)
        self._direct_memo: dict = {}  # qualname -> frozenset(call segments)
        self._reach_memo: dict = {}
        for m in self.modules:
            self._modnames[module_name(m.path)] = m
        for m in self.modules:
            self._index_module(m)
        for m in self.modules:
            self._link_imports(m)

    # -- construction -------------------------------------------------------

    def _index_module(self, module) -> None:
        modname = module_name(module.path)
        imports: dict = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        imports[alias.asname] = alias.name
                    else:
                        imports[head_segment(alias.name)] = \
                            head_segment(alias.name)
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    parts = modname.split(".")
                    anchor = parts[: max(0, len(parts) - node.level)]
                    if node.module:
                        anchor.append(node.module)
                    base = ".".join(anchor)
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    imports[alias.asname or alias.name] = (
                        f"{base}.{alias.name}" if base else alias.name)
        self._imports[module.path] = imports

        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                self._class_nodes.setdefault((modname, node.name), node)

        for fn, qual, classname in iter_functions(module.tree):
            fq = f"{modname}.{qual}"
            info = FunctionInfo(fn, module, modname, fq, fn.name, classname)
            self.functions[fq] = info
            self._fq_by_node[id(fn)] = fq
            self.by_name.setdefault(fn.name, []).append(fq)
            if classname is not None:
                self._methods.setdefault((modname, classname), {}) \
                    .setdefault(fn.name, fq)
            if classname is None:
                continue
            # `self._attr = fn` factory assignment: record the dotted RHS
            # so self._attr(...) resolves like lock-discipline does.
            for sub in walk_body_in_scope(fn.body):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    t = sub.targets[0]
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        rhs = dotted_name(sub.value)
                        if rhs and head_segment(rhs) != "self":
                            self._factories.setdefault(
                                (modname, classname), {}) \
                                .setdefault(t.attr, rhs)

    def _modpath_for(self, dotted: str) -> Optional[str]:
        cur = dotted
        while cur:
            m = self._modnames.get(cur)
            if m is not None:
                return m.path
            if "." not in cur:
                return None
            cur = cur.rsplit(".", 1)[0]
        return None

    def _link_imports(self, module) -> None:
        deps = set()
        for target in self._imports.get(module.path, {}).values():
            path = self._modpath_for(target)
            if path and path != module.path:
                deps.add(path)
        self.import_graph[module.path] = deps

    # -- resolution ---------------------------------------------------------

    def fq_of(self, funcnode) -> Optional[str]:
        return self._fq_by_node.get(id(funcnode))

    def class_node(self, modname: str, classname: str):
        return self._class_nodes.get((modname, classname))

    def resolve(self, dotted: Optional[str], modname: str,
                classname: Optional[str], module_path: str,
                _depth: int = 0) -> List[str]:
        """Qualnames a dotted callee may denote, [] when unresolvable.
        Exact where the name is local, imported, a method of the current
        class, or a ``self._attr = fn`` factory product."""
        if not dotted or _depth > 4:
            return []
        parts = dotted.split(".")
        head = parts[0]
        if head == "self":
            if classname is None or len(parts) < 2:
                return []
            attr = parts[1]
            methods = self._methods.get((modname, classname), {})
            if len(parts) == 2 and attr in methods:
                return [methods[attr]]
            factories = self._factories.get((modname, classname), {})
            if attr in factories:
                inner = ".".join([factories[attr]] + parts[2:])
                return self.resolve(inner, modname, classname,
                                    module_path, _depth + 1)
            return []
        fq = f"{modname}.{dotted}"
        if fq in self.functions:
            return [fq]
        imports = self._imports.get(module_path, {})
        if head in imports:
            target = ".".join([imports[head]] + parts[1:])
            if target in self.functions:
                return [target]
            # imported module alias: its own module-level def
            mpath = self._modpath_for(target)
            if mpath is not None and target in self.functions:
                return [target]
        return []

    def resolve_call(self, call: ast.Call, module, classname: Optional[str],
                     fallback: bool = True) -> List[str]:
        """Candidate qualnames for one call site. Unresolvable attribute
        calls fall back to bare-name matching (capped) when ``fallback``."""
        name = call_name(call)
        modname = module_name(module.path)
        resolved = self.resolve(name, modname, classname, module.path)
        if resolved:
            return resolved
        if fallback and name and "." in name:
            cands = self.by_name.get(last_segment(name), [])
            if 0 < len(cands) <= self._FALLBACK_CAP:
                return list(cands)
        return []

    # -- reachability -------------------------------------------------------

    def _direct_calls(self, fq: str) -> frozenset:
        cached = self._direct_memo.get(fq)
        if cached is not None:
            return cached
        segs = set()
        info = self.functions[fq]
        for sub in walk_body_in_scope(info.node.body):
            if isinstance(sub, ast.Call):
                segs.add(last_segment(call_name(sub)))
        out = frozenset(segs)
        self._direct_memo[fq] = out
        return out

    def _callees(self, fq: str) -> frozenset:
        cached = self._edges.get(fq)
        if cached is not None:
            return cached
        edges = set()
        info = self.functions[fq]
        for sub in walk_body_in_scope(info.node.body):
            if isinstance(sub, ast.Call):
                edges.update(self.resolve_call(
                    sub, info.module, info.classname))
        out = frozenset(edges)
        self._edges[fq] = out
        return out

    def reaches(self, fq: str, targets, depth: int = 5) -> bool:
        """True when ``fq`` (or anything it can call, ``depth`` hops of
        the call graph deep) makes a direct call whose last dotted
        segment is in ``targets``."""
        targets = frozenset(targets)
        key = (fq, targets, depth)
        cached = self._reach_memo.get(key)
        if cached is not None:
            return cached
        seen = {fq}
        frontier = [fq]
        hit = False
        for _ in range(depth + 1):
            if hit or not frontier:
                break
            nxt: List[str] = []
            for cur in frontier:
                if cur not in self.functions:
                    continue
                if self._direct_calls(cur) & targets:
                    hit = True
                    break
                for callee in self._callees(cur):
                    if callee not in seen:
                        seen.add(callee)
                        nxt.append(callee)
            frontier = nxt
        self._reach_memo[key] = hit
        return hit

    def call_hits(self, node: ast.AST, module, classname: Optional[str],
                  targets, depth: int = 4) -> int:
        """How many in-scope calls under ``node`` hit ``targets`` —
        directly, or through any resolvable callee (cross-module)."""
        targets = frozenset(targets)
        n = 0
        for sub in walk_in_scope(node):
            if not isinstance(sub, ast.Call):
                continue
            if last_segment(call_name(sub)) in targets:
                n += 1
                continue
            for fq in self.resolve_call(sub, module, classname):
                if self.reaches(fq, targets, depth):
                    n += 1
                    break
        return n

    # -- import graph queries ----------------------------------------------

    def reverse_dependencies(self, paths) -> set:
        """``paths`` plus every module that (transitively) imports one of
        them — the blast radius of a change, for ``--changed`` runs."""
        rev: dict = {}
        for src, deps in self.import_graph.items():
            for d in deps:
                rev.setdefault(d, set()).add(src)
        out = set(paths)
        frontier = list(out)
        while frontier:
            p = frontier.pop()
            for src in rev.get(p, ()):
                if src not in out:
                    out.add(src)
                    frontier.append(src)
        return out
