"""Shared AST helpers for the tpumnist-lint checkers.

Pure stdlib ``ast`` — no imports of the analyzed code. Everything here is
syntactic: dotted-name rendering, scope walks that respect function
boundaries, and small predicates the checkers share so their notion of
"a call to X" cannot drift from one another.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Sequence, Tuple

#: Node types that open a new runtime scope — traversals that reason about
#: "code executed here" must not descend into these.
SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)


def dotted_name(node: ast.AST) -> Optional[str]:
    """Render ``a.b.c`` for a Name/Attribute chain; None for anything
    dynamic (subscripts, call results) — callers treat None as 'unknown',
    never as a match."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def call_name(node: ast.Call) -> Optional[str]:
    return dotted_name(node.func)


def last_segment(name: Optional[str]) -> str:
    return name.rsplit(".", 1)[-1] if name else ""


def head_segment(name: Optional[str]) -> str:
    return name.split(".", 1)[0] if name else ""


def walk_in_scope(node: ast.AST) -> Iterator[ast.AST]:
    """Yield ``node``'s subtree WITHOUT entering nested function/class
    scopes: the statements that actually execute when this scope runs.
    ``node`` itself is yielded (unless it is a scope node being entered
    from outside — callers pass a function's *body* items, not the def)."""
    stack: List[ast.AST] = [node]
    while stack:
        cur = stack.pop()
        yield cur
        for child in ast.iter_child_nodes(cur):
            if isinstance(child, SCOPE_NODES):
                continue
            stack.append(child)


def walk_body_in_scope(body: Sequence[ast.stmt]) -> Iterator[ast.AST]:
    for stmt in body:
        yield from walk_in_scope(stmt)


def iter_functions(
    tree: ast.AST,
) -> Iterator[Tuple[ast.AST, str, Optional[str]]]:
    """Yield ``(funcnode, qualname, classname)`` for every function in the
    module, nested ones included. ``qualname`` is dotted through the
    enclosing defs/classes; ``classname`` is the nearest enclosing class
    (None at module level) — the lock checker keys lock objects by it."""

    def visit(node: ast.AST, prefix: str, classname: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                yield child, qual, classname
                yield from visit(child, qual, classname)
            elif isinstance(child, ast.ClassDef):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                yield from visit(child, qual, child.name)
            else:
                yield from visit(child, prefix, classname)

    yield from visit(tree, "", None)


def defs_by_name(tree: ast.AST) -> dict:
    """``{name: [def nodes]}`` over the whole module, nested defs included
    — the shared "resolve a bare callee name" index (trace-purity's call
    graph and recompile-hazard's jit-site lookup must agree on it)."""
    defs: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    return defs


def function_param_names(fn: ast.AST) -> List[str]:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return names


def handler_type_names(handler: ast.ExceptHandler) -> List[str]:
    """The dotted names an ``except`` clause catches; ``[]`` for a bare
    ``except:``. Unresolvable entries (dynamic expressions) render as
    ``"<dynamic>"`` so breadth checks stay conservative."""
    t = handler.type
    if t is None:
        return []
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    return [dotted_name(e) or "<dynamic>" for e in elts]


def contains_call_to(node: ast.AST, last_segments: set) -> bool:
    """True when ``node``'s in-scope subtree calls any function whose last
    dotted segment is in ``last_segments``."""
    for sub in walk_in_scope(node):
        if isinstance(sub, ast.Call) and \
                last_segment(call_name(sub)) in last_segments:
            return True
    return False


def body_contains_any_call(body: Sequence[ast.stmt]) -> bool:
    for sub in walk_body_in_scope(body):
        if isinstance(sub, ast.Call):
            return True
    return False


def body_contains_raise(body: Sequence[ast.stmt]) -> bool:
    for sub in walk_body_in_scope(body):
        if isinstance(sub, ast.Raise):
            return True
    return False


def str_constants(node: ast.AST) -> List[str]:
    """String literals inside a tuple/list/single-constant expression."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
        return out
    return []


def int_constants(node: ast.AST) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int) \
                    and not isinstance(e.value, bool):
                out.append(e.value)
        return out
    return []
