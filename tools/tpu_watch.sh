#!/bin/bash
# Poll for TPU recovery; when the chip answers, capture the round's
# perf evidence (bench + north star) into tools/captured/.
# Session utility for the intermittent chip tunnel — safe to re-run.
set -u
OUT=/root/repo/tools/captured
mkdir -p "$OUT"
while true; do
  if timeout 90 python -c "import jax; assert jax.default_backend()=='tpu'; import jax.numpy as jnp; float(jnp.sum(jnp.ones((8,8))))" >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) TPU alive - capturing" >> "$OUT/watch.log"
    timeout 900 python /root/repo/bench.py > "$OUT/bench.json" 2>> "$OUT/watch.log"
    BENCH_RC=$?
    timeout 1800 python /root/repo/tools/northstar.py \
      --dataset synthetic --epochs 20 --batch-size 512 --target 0.99 \
      --compile-cache /tmp/ns_xla_cache \
      --root /tmp/ns_tpu > "$OUT/northstar.json" 2>> "$OUT/watch.log"
    NS_RC=$?
    echo "$(date -u +%FT%TZ) capture done bench_rc=$BENCH_RC northstar_rc=$NS_RC" >> "$OUT/watch.log"
    if [ "$BENCH_RC" -ne 0 ] || [ "$NS_RC" -ne 0 ]; then
      echo "$(date -u +%FT%TZ) capture INCOMPLETE - will retry" >> "$OUT/watch.log"
      sleep 300
      continue
    fi
    # On-chip kernel/training suite (Mosaic compiles of all three Pallas
    # kernels + the fused-path training run); once per successful round,
    # after the retry gate so a flaky bench never re-runs or clobbers it.
    timeout 1800 python -m pytest /root/repo/tests_tpu/ -q \
      > "$OUT/tests_tpu.log" 2>&1
    TT_RC=$?
    echo "$(date -u +%FT%TZ) tests_tpu rc=$TT_RC (see tests_tpu.log)" >> "$OUT/watch.log"
    exit 0
  fi
  echo "$(date -u +%FT%TZ) tpu still down" >> "$OUT/watch.log"
  sleep 300
done
