#!/bin/bash
# Poll for TPU recovery; when the chip answers, capture the round's
# perf evidence (bench + north star) into tools/captured/.
# Session utility for the intermittent chip tunnel — safe to re-run.
set -u
OUT=/root/repo/tools/captured
mkdir -p "$OUT"
while true; do
  if timeout 90 python -c "import jax; assert jax.default_backend()=='tpu'; import jax.numpy as jnp; float(jnp.sum(jnp.ones((8,8))))" >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) TPU alive - capturing" >> "$OUT/watch.log"
    timeout 900 python /root/repo/bench.py > "$OUT/bench.json" 2>> "$OUT/watch.log"
    timeout 1800 python /root/repo/tools/northstar.py \
      --dataset synthetic --epochs 20 --batch-size 512 --target 0.99 \
      --root /tmp/ns_tpu > "$OUT/northstar.json" 2>> "$OUT/watch.log"
    echo "$(date -u +%FT%TZ) capture done rc=$?" >> "$OUT/watch.log"
    exit 0
  fi
  echo "$(date -u +%FT%TZ) tpu still down" >> "$OUT/watch.log"
  sleep 300
done
