#!/bin/bash
# Poll for TPU recovery; when the chip answers, capture the round's
# perf evidence (bench + north star) into tools/captured/.
# Session utility for the intermittent chip tunnel — safe to re-run.
set -u
OUT=/root/repo/tools/captured
mkdir -p "$OUT"
# Shared persistent compile cache: whatever the watcher compiles here, the
# driver's end-of-round bench.py reuses (BENCH_COMPILE_CACHE default), so a
# recovered chip never pays the compile minutes twice.
export BENCH_COMPILE_CACHE=/root/repo/.xla_cache
while true; do
  if timeout 90 python -c "import jax; assert jax.default_backend()=='tpu'; import jax.numpy as jnp; float(jnp.sum(jnp.ones((8,8))))" >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) TPU alive - capturing" >> "$OUT/watch.log"
    # Write to a temp file and publish only a freshly measured TPU line: a
    # wedged retry must never truncate or downgrade an earlier good capture
    # (bench.py's own watcher-capture fallback reads bench.json), and
    # BENCH_CAPTURE_PATH= disables that fallback here so bench.py can never
    # re-emit this watcher's own prior output as a "new" capture.
    # Timeout covers bench.py's worst-case internal ladder (~30 min).
    BENCH_CAPTURE_PATH= timeout 2400 python /root/repo/bench.py > "$OUT/bench.json.new" 2>> "$OUT/watch.log"
    BENCH_RC=$?
    if grep -q '"backend": "tpu"' "$OUT/bench.json.new" 2>/dev/null \
        && ! grep -q '"source": "watcher_capture"' "$OUT/bench.json.new" 2>/dev/null; then
      mv "$OUT/bench.json.new" "$OUT/bench.json"
    else
      echo "$(date -u +%FT%TZ) bench output not TPU-backed - kept prior capture" >> "$OUT/watch.log"
      cat "$OUT/bench.json.new" >> "$OUT/watch.log" 2>/dev/null
      rm -f "$OUT/bench.json.new"
      BENCH_RC=1
    fi
    timeout 1800 python /root/repo/tools/northstar.py \
      --dataset synthetic --epochs 20 --batch-size 512 --target 0.99 \
      --compile-cache "$BENCH_COMPILE_CACHE" \
      --root /tmp/ns_tpu > "$OUT/northstar.json.new" 2>> "$OUT/watch.log"
    NS_RC=$?
    if [ "$NS_RC" -eq 0 ]; then
      mv "$OUT/northstar.json.new" "$OUT/northstar.json"
    else
      cat "$OUT/northstar.json.new" >> "$OUT/watch.log" 2>/dev/null
      rm -f "$OUT/northstar.json.new"
    fi
    echo "$(date -u +%FT%TZ) capture done bench_rc=$BENCH_RC northstar_rc=$NS_RC" >> "$OUT/watch.log"
    # Captures are round evidence: commit them the moment they exist, so a
    # chip that answers at 3am still produces a timestamped git record.
    # Pathspec'd commit: never scoop whatever the interactive session has
    # staged into the watcher's background commit.
    git -C /root/repo add tools/captured \
      && git -C /root/repo commit -q \
        -m "tools/captured: TPU capture bench_rc=$BENCH_RC northstar_rc=$NS_RC" \
        -- tools/captured >> "$OUT/watch.log" 2>&1
    if [ "$BENCH_RC" -ne 0 ] || [ "$NS_RC" -ne 0 ]; then
      echo "$(date -u +%FT%TZ) capture INCOMPLETE - will retry" >> "$OUT/watch.log"
      sleep 300
      continue
    fi
    # MXU-bound kernel benchmarks (flash vs dense attention, fused Adam vs
    # optax) + on-chip kernel/training suite (Mosaic compiles of all three
    # Pallas kernels); once per successful round, after the retry gate so a
    # flaky bench never re-runs or clobbers them.
    timeout 1800 python /root/repo/tools/bench_kernels.py \
      > "$OUT/kernels.json" 2>> "$OUT/watch.log"
    KB_RC=$?
    echo "$(date -u +%FT%TZ) kernel bench rc=$KB_RC" >> "$OUT/watch.log"
    timeout 1800 python -m pytest /root/repo/tests_tpu/ -q \
      > "$OUT/tests_tpu.log" 2>&1
    TT_RC=$?
    echo "$(date -u +%FT%TZ) tests_tpu rc=$TT_RC (see tests_tpu.log)" >> "$OUT/watch.log"
    git -C /root/repo add tools/captured \
      && git -C /root/repo commit -q \
        -m "tools/captured: kernel bench rc=$KB_RC, tests_tpu rc=$TT_RC" \
        -- tools/captured >> "$OUT/watch.log" 2>&1
    exit 0
  fi
  echo "$(date -u +%FT%TZ) tpu still down" >> "$OUT/watch.log"
  sleep 300
done
