#!/bin/bash
# Round-5 capture watcher. Supersedes tools/tpu_watch_r4.sh (whose slate
# never landed: the chip was down from 04:10Z Jul 30 through the whole of
# round 4 — tools/captured/watch.log).
#
# What must land at the next chip recovery, in priority order (round-4
# VERDICT "Next round" items 1-5):
#   1. kernels.json          — tools/bench_kernels.py with host-read sync
#                              + impossibility guards (the only prior
#                              capture, kernels_r3_invalid.json, recorded
#                              a physically impossible sync and was
#                              invalidated — flash/fused-Adam claims rest
#                              on NO valid measurement until this lands).
#   2. tests_tpu_rerun.log   — the on-chip suite with the staged fixes
#                              (expect green; 6/9 pre-fix).
#   3. northstar_cold_r5.json — cold start against the shipped .xla_cache
#                              (primed-cache cold: the honest "first run"
#                              figure; also (re)warms the cache), with the
#                              round-5 host-gather default.
#   4. northstar_warm.json   — the SAME command immediately after: compile
#                              cache hot, the steady-state <60 s figure.
#   5. flash_sweep.json      — block-size sweep behind the T=4096
#                              flash-vs-dense decision.
#   6. bench.json            — fresh headline line (also carries the
#                              device-gather + sorted-index probe numbers
#                              that decide VERDICT #4 by measurement).
#   7. bench_vit.json        — end-to-end MXU-bound ViT line; --vit now
#                              exits nonzero on full failure (round-4
#                              advisor), so the rc gate is real.
#
# Publication gates per item: producer exit code 0, a required
# '"backend": "tpu"' marker (a producer whose jax init fell back to CPU
# exits 0 with an honest CPU line — that must never become the round's
# capture), and for bench.json the absence of the watcher-capture
# re-emission marker. Each item is skipped once captured; a 90s liveness
# re-probe before each item skips the rest of a cycle when the link
# wedges mid-way. Retry cycles are CAPPED (round-3 advisor: the uncapped
# followup loop could churn one commit per attempt forever).
set -u
OUT=/root/repo/tools/captured
STATE=/tmp/tpu_watch_r5_state
mkdir -p "$OUT" "$STATE"
export BENCH_COMPILE_CACHE=/root/repo/.xla_cache
MAX_CYCLES=6
CYCLES=0

log() { echo "$(date -u +%FT%TZ) $*" >> "$OUT/watch.log"; }

probe_tpu() {
  timeout 90 python -c "import jax; assert jax.default_backend()=='tpu'; import jax.numpy as jnp; float(jnp.sum(jnp.ones((8,8))))" >/dev/null 2>&1
}

# run_capture <name> <timeout> <dest> <require_pat> <forbid_pat> <cmd...>
# stdout -> dest.new; published to dest only when rc==0 AND require_pat
# (if non-empty) is present AND forbid_pat (if non-empty) is absent.
# Marks $STATE/<name> on success so later cycles skip it.
run_capture() {
  local name=$1 tmo=$2 dest=$3 require=$4 forbid=$5; shift 5
  [ -e "$STATE/$name" ] && return 0
  if ! probe_tpu; then
    log "r5 capture $name skipped: link re-probe failed"
    return 1
  fi
  timeout "$tmo" "$@" > "$dest.new" 2>> "$OUT/watch.log"
  local rc=$?
  if [ "$rc" -eq 0 ] && [ -n "$require" ] \
      && ! grep -q "$require" "$dest.new" 2>/dev/null; then
    log "r5 capture $name rejected: missing required marker $require"
    rc=1
  fi
  if [ "$rc" -eq 0 ] && [ -n "$forbid" ] \
      && grep -q "$forbid" "$dest.new" 2>/dev/null; then
    log "r5 capture $name rejected: forbidden marker $forbid"
    rc=1
  fi
  if [ "$rc" -eq 0 ]; then
    mv "$dest.new" "$dest"
    touch "$STATE/$name"
  else
    cat "$dest.new" >> "$OUT/watch.log" 2>/dev/null
    rm -f "$dest.new"
  fi
  log "r5 capture $name rc=$rc"
  return "$rc"
}

TPU='"backend": "tpu"'

while true; do
  if probe_tpu; then
    log "TPU alive - r5 capturing (cycle $((CYCLES + 1))/$MAX_CYCLES)"
    # Wait out any hermetic-suite run: one host core; a concurrent
    # pytest would pollute every wall-clock number below. 80x30s covers
    # the full suite (~35 min, README); if pytest is SOMEHOW still alive
    # after that, say so in the log — silently capturing contended
    # wall-clock numbers would violate the same no-silent-pollution rule
    # the rc gates enforce.
    for i in $(seq 1 80); do
      pgrep -f "pytest /root/repo/tests/" >/dev/null 2>&1 || \
        pgrep -f "pytest tests/" >/dev/null 2>&1 || break
      if [ "$i" -eq 80 ]; then
        log "r5 WARNING: pytest still running after 40 min wait - captures below may be CPU-contended"
      fi
      sleep 30
    done

    run_capture kernels 1800 "$OUT/kernels.json" "$TPU" "" \
      python /root/repo/tools/bench_kernels.py; K_RC=$?

    # pytest writes its own log (stdout IS the artifact, failing or not)
    # but only a green run marks the item done.
    if [ ! -e "$STATE/tests_tpu" ]; then
      if probe_tpu; then
        timeout 1800 python -m pytest /root/repo/tests_tpu/ -q \
          > "$OUT/tests_tpu_rerun.log" 2>&1
        T_RC=$?
        # The suite SKIPS (rc 0) when the link wedges between our probe
        # and pytest's own; an all-skipped log is not a green run.
        if [ "$T_RC" -eq 0 ] \
            && grep -q "no TPU backend reachable" "$OUT/tests_tpu_rerun.log"; then
          log "r5 capture tests_tpu rejected: suite skipped (link dropped)"
          T_RC=1
        fi
        [ "$T_RC" -eq 0 ] && touch "$STATE/tests_tpu"
        log "r5 capture tests_tpu rc=$T_RC (tests_tpu_rerun.log)"
      else
        T_RC=1
        log "r5 capture tests_tpu skipped: link re-probe failed"
      fi
    else
      T_RC=0
    fi

    # Cold/warm pair: SAME command twice, back to back. The first run is
    # the primed-cache cold start (fresh process against whatever
    # .xla_cache already holds — the honest "first run" a user pays, and
    # it leaves the cache hot); the second is the steady-state warm
    # number for the <60 s target. Both use the round-5 host-gather
    # default (tools/northstar.py); --epoch-gather device stays
    # measurable by hand if bench.json's probe says it wins after all.
    run_capture northstar_cold 1800 "$OUT/northstar_cold_r5.json" "$TPU" "" \
      python /root/repo/tools/northstar.py \
        --dataset synthetic --epochs 20 --batch-size 512 --target 0.99 \
        --compile-cache "$BENCH_COMPILE_CACHE" \
        --root /tmp/ns_tpu_cold_r5; NC_RC=$?

    run_capture northstar_warm 1800 "$OUT/northstar_warm.json" "$TPU" "" \
      python /root/repo/tools/northstar.py \
        --dataset synthetic --epochs 20 --batch-size 512 --target 0.99 \
        --compile-cache "$BENCH_COMPILE_CACHE" \
        --root /tmp/ns_tpu_warm; N_RC=$?

    run_capture flash_sweep 2400 "$OUT/flash_sweep.json" "$TPU" "" \
      python /root/repo/tools/sweep_flash.py; F_RC=$?

    # BENCH_CAPTURE_PATH= disables bench.py's own watcher-capture
    # fallback so it can never re-emit this watcher's prior output; the
    # forbid marker rejects it even if that plumbing regresses.
    # BENCH_LAST_CAPTURE_PATH= disables the round-5 provenance pointer:
    # a capture must never embed a pointer to its own predecessor.
    run_capture bench 2400 "$OUT/bench.json" "$TPU" '"source": "watcher_capture"' \
      env BENCH_CAPTURE_PATH= BENCH_LAST_CAPTURE_PATH= \
        python /root/repo/bench.py; B_RC=$?

    run_capture bench_vit 2400 "$OUT/bench_vit.json" "$TPU" "" \
      env BENCH_CAPTURE_PATH= BENCH_LAST_CAPTURE_PATH= \
        python /root/repo/bench.py --vit; V_RC=$?

    log "r5 cycle done kernels=$K_RC tests_tpu=$T_RC northstar_cold=$NC_RC northstar_warm=$N_RC flash_sweep=$F_RC bench=$B_RC bench_vit=$V_RC"
    git -C /root/repo add tools/captured \
      && git -C /root/repo commit -q \
        -m "tools/captured: r5 capture kernels=$K_RC tests_tpu=$T_RC northstar_cold=$NC_RC northstar_warm=$N_RC flash_sweep=$F_RC bench=$B_RC bench_vit=$V_RC" \
        -- tools/captured >> "$OUT/watch.log" 2>&1
    if [ "$K_RC" -eq 0 ] && [ "$T_RC" -eq 0 ] && [ "$NC_RC" -eq 0 ] \
        && [ "$N_RC" -eq 0 ] && [ "$F_RC" -eq 0 ] && [ "$B_RC" -eq 0 ] \
        && [ "$V_RC" -eq 0 ]; then
      log "r5 capture COMPLETE"
      exit 0
    fi
    CYCLES=$((CYCLES + 1))
    if [ "$CYCLES" -ge "$MAX_CYCLES" ]; then
      log "r5 capture INCOMPLETE after $MAX_CYCLES cycles - giving up"
      exit 1
    fi
    log "r5 capture INCOMPLETE - will retry ($CYCLES/$MAX_CYCLES used)"
    sleep 300
    continue
  fi
  echo "$(date -u +%FT%TZ) tpu still down (r5)" >> "$OUT/watch.log"
  sleep 390
done
