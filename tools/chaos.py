#!/usr/bin/env python
"""Fault-injection (chaos) harness for the run-supervision subsystem.

Drives the same local N-process world as ``tpu-mnist --spawn`` with ONE
process sabotaged at a named fault point (``runtime/supervision.py``'s
``TPUMNIST_FAULT=point:host:kind[:arg]`` hook, comma-join for multiple
faults), so the agreed-exit protocol, the collective watchdogs, and the
elastic shrink-don't-exit runtime can be exercised against real process
deaths instead of monkeypatches:

    # what can be injected, and where each point fires
    python tools/chaos.py --list

    # SIGKILL host 0 right before the checkpoint publish agreement;
    # host 1 must exit with PeerFailure within the deadline, not hang
    python tools/chaos.py --fault ckpt_publish:0:kill --nprocs 2 \\
        --agreement-timeout 10 -- \\
        --dataset synthetic --model linear --epochs 2 \\
        --optimizer-sharding zero1 --trainer-mode stepwise

    # then prove recovery: the same world, no fault, resumes
    python tools/chaos.py --nprocs 2 -- --dataset synthetic \\
        --model linear --epochs 2 --optimizer-sharding zero1 \\
        --trainer-mode stepwise --resume auto

    # ELASTIC: kill host 1 mid-run and watch the world SHRINK instead
    # of exit — the survivor is re-execed as a 1-host world resumed
    # from the last published checkpoint and trains to completion
    python tools/chaos.py --elastic --fault train_epoch:1:kill:1 \\
        --nprocs 2 -- --dataset synthetic --model linear --epochs 3 \\
        --optimizer-sharding zero1 --trainer-mode stepwise

    # mid-REBUILD second failure: host 2 dies, then host 1 stalls while
    # writing its survivor record — the supervisor's settle deadline
    # kills the straggler and the world shrinks to host 0 alone
    python tools/chaos.py --elastic --min-world 1 --nprocs 3 \\
        --fault "resume:2:kill,elastic_rebuild:1:stall" -- \\
        --dataset synthetic --model linear --epochs 3 --batch-size 48 \\
        --trainer-mode stepwise --resume auto

Fault host indices are process RANKS within the world that reads the
plan — in an elastic run each rebuilt generation renumbers its ranks
0..W'-1, so a spec aimed at rank 2 cannot re-fire once the world is
smaller than 3 (the usual way to target "the first failure only").
For a shrink to happen the survivors must reach a HOST-side failure
(an agreement, or a transport error): at 3+ ranks a kill mid-device-
program parks the others in a timeout-less gloo collective — bounded
by the supervisor's settle deadline, but recordless ranks count dead
(the residual-hazard boundary in docs/DESIGN.md) — so aim elastic
faults at supervised phases (resume, ckpt_*) on worlds above 2.

Exit code: 0 when every rank exited 0 (for elastic runs: the job
trained to completion on whatever world remained); otherwise the first
failing rank's code (killed ranks surface as 128+signal; an elastic
shrink past --min-world exits the supervisor's floor code).
tests/test_chaos.py and tests/test_elastic_chaos.py run these scenarios
with assertions; this tool is the operator-facing way to reproduce one
interactively.

``--list`` is the drift gate: tests/test_supervision.py pins that its
output, the ``FAULT_POINTS`` registry, and the ``maybe_fault()`` call
sites in the source all agree — a hook added without registry+docs (or
vice versa) fails the suite.
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from pytorch_distributed_mnist_tpu.parallel.launcher import (  # noqa: E402
    spawn_local,
)
from pytorch_distributed_mnist_tpu.runtime.elastic import (  # noqa: E402
    supervise,
)
from pytorch_distributed_mnist_tpu.runtime.supervision import (  # noqa: E402
    FAULT_ENV,
    FAULT_POINTS,
    TIMEOUT_ENV,
    parse_fault_specs,
)


def list_fault_points(file=sys.stdout) -> None:
    """One line per injectable point: ``name<TAB>description``."""
    for name in sorted(FAULT_POINTS):
        print(f"{name}\t{FAULT_POINTS[name]}", file=file)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="chaos",
        description="fault-injection twins for the run-supervision layer",
    )
    p.add_argument("--list", action="store_true",
                   help="enumerate injectable fault points and exit")
    p.add_argument("--fault", type=str, default=None,
                   metavar="POINT:HOST:KIND[:ARG][,...]",
                   help="the fault(s) to inject (see --list; kinds: "
                        "kill, raise, stall; comma-join for multiple, "
                        "e.g. a host loss plus an elastic_rebuild "
                        "sabotage of a survivor). Omit for a clean "
                        "control run")
    p.add_argument("--elastic", action="store_true",
                   help="run under the elastic supervisor "
                        "(runtime/elastic.py): a host loss SHRINKS the "
                        "world — survivors re-exec at the smaller size "
                        "and resume from the last published checkpoint "
                        "— instead of ending the run")
    p.add_argument("--min-world", type=int, default=1, metavar="W",
                   help="elastic floor: stop shrinking below W healthy "
                        "hosts (default 1)")
    p.add_argument("--settle-timeout", type=float, default=60.0,
                   help="elastic: seconds the supervisor waits for the "
                        "remaining ranks to exit once one has failed, "
                        "before killing stragglers and shrinking "
                        "without them (default 60)")
    p.add_argument("--nprocs", type=int, default=2,
                   help="local host processes to spawn (default 2)")
    p.add_argument("--agreement-timeout", type=float, default=15.0,
                   help="watchdog deadline handed to every rank via "
                        f"{TIMEOUT_ENV} (default 15s: chaos runs WANT "
                        "the watchdog — a hang is the bug under test)")
    p.add_argument("--timeout", type=float, default=600.0,
                   help="whole-run wall clock bound before every rank "
                        "is killed (default 600s); for elastic runs, "
                        "the per-generation bound")
    p.add_argument("cli_args", nargs=argparse.REMAINDER,
                   help="arguments after -- go to tpu-mnist verbatim")
    args = p.parse_args(argv)

    if args.list:
        list_fault_points()
        return 0

    if args.fault:
        parse_fault_specs(args.fault)  # fail fast with the spec's message
        os.environ[FAULT_ENV] = args.fault
    else:
        os.environ.pop(FAULT_ENV, None)
    os.environ[TIMEOUT_ENV] = str(args.agreement_timeout)

    cli_args = list(args.cli_args)
    if cli_args and cli_args[0] == "--":
        cli_args = cli_args[1:]
    print(f"chaos: spawning {args.nprocs} ranks"
          + (" under the elastic supervisor" if args.elastic else "")
          + (f", fault {args.fault}" if args.fault else " (control run)")
          + f", agreement timeout {args.agreement_timeout:g}s",
          file=sys.stderr)
    if args.elastic:
        return supervise(
            args.nprocs, cli_args, min_world=args.min_world,
            settle_timeout=args.settle_timeout,
            generation_timeout=args.timeout)
    return spawn_local(args.nprocs, cli_args, timeout=args.timeout)


if __name__ == "__main__":
    sys.exit(main())
