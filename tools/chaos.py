#!/usr/bin/env python
"""Fault-injection (chaos) harness for the run-supervision subsystem.

Drives the same local N-process world as ``tpu-mnist --spawn`` with ONE
process sabotaged at a named fault point (``runtime/supervision.py``'s
``TPUMNIST_FAULT=point:host:kind[:arg]`` hook, comma-join for multiple
faults), so the agreed-exit protocol, the collective watchdogs, and the
elastic shrink/grow runtime can be exercised against real process
deaths instead of monkeypatches:

    # what can be injected, and where each point fires
    python tools/chaos.py --list

    # SIGKILL host 0 right before the checkpoint publish agreement;
    # host 1 must exit with PeerFailure within the deadline, not hang
    python tools/chaos.py --fault ckpt_publish:0:kill --nprocs 2 \\
        --agreement-timeout 10 -- \\
        --dataset synthetic --model linear --epochs 2 \\
        --optimizer-sharding zero1 --trainer-mode stepwise

    # then prove recovery: the same world, no fault, resumes
    python tools/chaos.py --nprocs 2 -- --dataset synthetic \\
        --model linear --epochs 2 --optimizer-sharding zero1 \\
        --trainer-mode stepwise --resume auto

    # ELASTIC: kill host 1 mid-run and watch the world SHRINK instead
    # of exit — the survivor is re-execed as a 1-host world resumed
    # from the last published checkpoint and trains to completion
    python tools/chaos.py --elastic --fault train_epoch:1:kill:1 \\
        --nprocs 2 -- --dataset synthetic --model linear --epochs 3 \\
        --optimizer-sharding zero1 --trainer-mode stepwise

    # mid-REBUILD second failure: host 2 dies, then host 1 stalls while
    # writing its survivor record — the supervisor's settle deadline
    # kills the straggler and the world shrinks to host 0 alone
    python tools/chaos.py --elastic --min-world 1 --nprocs 3 \\
        --fault "resume:2:kill,elastic_rebuild:1:stall" -- \\
        --dataset synthetic --model linear --epochs 3 --batch-size 48 \\
        --trainer-mode stepwise --resume auto

    # GROW (2 -> 1 -> 2): host 1 dies mid-epoch, the world shrinks to
    # host 0; --rejoin 1@1 then writes host 1's join record while
    # generation 1 runs, the next epoch-boundary grow rendezvous admits
    # it, and the job finishes back at world size 2
    python tools/chaos.py --elastic --elastic-grow --rejoin 1@1 \\
        --fault train_step:1:kill:5 --nprocs 2 -- \\
        --dataset synthetic --model linear --epochs 3 \\
        --optimizer-sharding zero1 --trainer-mode stepwise

    # SLICE LOSS on the emulated hierarchical mesh: the 2-host world
    # runs as 2 DCN slices x 1 host; killing every host of slice 1
    # shrinks it to the surviving slice, whose 1-host world the slice
    # count no longer divides — it lands on the FLAT mesh (cli.py's
    # elastic fallback) and resumes via the ordinary (W, W') reshard
    python tools/chaos.py --elastic --dcn-slices 2 --kill-slice 1 \\
        --nprocs 2 -- --dataset synthetic --model linear --epochs 3 \\
        --optimizer-sharding zero1 --trainer-mode stepwise

    # SERVE-POOL self-healing: boot a real 4-replica server, 'kill'
    # group 1 after 5 batches (TPUMNIST_SERVE_FAULT injection), hammer
    # it with loadgen — every request must answer 200 (failover, never
    # a drop), the pool must quarantine + regroup, and the final smoke
    # asserts all 4 groups active again
    python tools/chaos.py --serve --serve-devices 4 --serve-fault 0:5 \\
        --expect-groups 4 --requests 400 --cpu-devices 4

    # rolling topology change: /resize 2 -> 4 -> 2 replicas under live
    # traffic; zero dropped requests end to end
    python tools/chaos.py --serve --serve-devices 2 --resize 4,2 \\
        --expect-groups 2 --requests 400 --cpu-devices 4

    # AUTOSCALER: spike load against a 1-device pool with --autoscale.
    # Phase 1 (dry run) asserts the controller DECIDED to scale up
    # without touching the topology; phase 2 asserts the real resize
    # up during the spike and back down after it — zero dropped
    # in-flight requests, Retry-After on every shed
    python tools/chaos.py --autoscale-spike --cpu-devices 2

    # QUOTA ABUSE: one hot client at 10x --quota-rps is clipped with
    # 429 + Retry-After while the well-behaved client keeps >= 90%
    # goodput — one abuser cannot starve the rest
    python tools/chaos.py --quota-abuse --cpu-devices 2 --quota-rps 20

    # FLEET: a real router over 3 real backends; SIGKILL backend 1
    # mid-loadgen — zero DROPPED requests (failover + bounded client
    # retry), quarantine, then probation re-admission after a restart
    python tools/chaos.py --fleet 3 --kill-backend 1 --cpu-devices 1

    # fleet-wide rolling deploy under live traffic: every backend on
    # the new epoch, zero drops
    python tools/chaos.py --fleet 3 --rolling-reload --cpu-devices 1

    # a publish that fails the fleet canary rolls back with the
    # baseline weights republished and still serving
    python tools/chaos.py --fleet 2 --fleet-canary-rollback \\
        --cpu-devices 1

    # DELTA DISTRIBUTION: 3 backends watch one shared checkpoint dir;
    # 3 adjacent delta publishes under live loadgen — zero drops,
    # every backend converges, and each publish's new chunk bytes are
    # a tiny fraction of the cold (whole-state) publish
    python tools/chaos.py --fleet 3 --delta-publish 3 --cpu-devices 1

    # torn publish: a half-written manifest, then a manifest with a
    # missing chunk, then a clean one — skipped, skipped, recovered;
    # serving never stops through any of it
    python tools/chaos.py --torn-manifest --cpu-devices 1

Fault host indices are process RANKS within the world that reads the
plan — in an elastic run each rebuilt generation renumbers its ranks
0..W'-1, so a spec aimed at rank 2 cannot re-fire once the world is
smaller than 3 (the usual way to target "the first failure only").
For a shrink to happen the survivors must reach a HOST-side failure
(an agreement, or a transport error): at 3+ ranks a kill mid-device-
program parks the others in a timeout-less gloo collective — bounded
by the supervisor's settle deadline, but recordless ranks count dead
(the residual-hazard boundary in docs/DESIGN.md) — so aim elastic
faults at supervised phases (resume, ckpt_*) on worlds above 2.

Exit code: 0 when every rank exited 0 (for elastic runs: the job
trained to completion on whatever world remained; for serve runs: zero
dropped requests AND the expected post-heal topology); otherwise the
first failing rank's code (killed ranks surface as 128+signal; an
elastic shrink past --min-world exits the supervisor's floor code).
tests/test_chaos.py, tests/test_elastic_chaos.py, and
tests/test_serve_heal_server.py run these scenarios with assertions;
this tool is the operator-facing way to reproduce one interactively.

``--list`` is the drift gate: tests/test_supervision.py pins that its
output, the ``FAULT_POINTS`` registry, and the ``maybe_fault()`` call
sites in the source all agree — a hook added without registry+docs (or
vice versa) fails the suite.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import re
import shutil
import subprocess
import sys
import tempfile
import time
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from pytorch_distributed_mnist_tpu.parallel.launcher import (  # noqa: E402
    spawn_local,
)
from pytorch_distributed_mnist_tpu.runtime.elastic import (  # noqa: E402
    supervise,
)
from pytorch_distributed_mnist_tpu.runtime.supervision import (  # noqa: E402
    FAULT_ENV,
    FAULT_POINTS,
    TIMEOUT_ENV,
    parse_fault_specs,
)

# serve/pool.py::SERVE_FAULT_ENV, spelled out so the chaos CLI stays
# jax-import-free until a twin actually runs (pinned equal by
# tests/test_serve_heal_server.py).
SERVE_FAULT_ENV = "TPUMNIST_SERVE_FAULT"

# serve/canary.py::CANARY_FAULT_ENV, spelled out for the same
# jax-import-free reason (pinned equal by tests/test_serve_canary.py):
# the --canary-rollback twin sets it to "disagree" so every shadow
# comparison fails the budget.
CANARY_FAULT_ENV = "TPUMNIST_CANARY_FAULT"

# serve/router.py::FLEET_FAULT_ENV, spelled out for the same
# jax-import-free reason (pinned equal by tests/test_serve_router.py):
# the --fleet-canary-rollback twin sets it to "canary_disagree" in the
# ROUTER's environment so every fleet-canary cohort row disagrees.
FLEET_FAULT_ENV = "TPUMNIST_FLEET_FAULT"

# parallel/mesh.py::DCN_SLICES_ENV, spelled out for the same
# jax-import-free reason (pinned equal by tests/test_hier_mesh.py).
DCN_SLICES_ENV = "TPUMNIST_DCN_SLICES"


def list_fault_points(file=sys.stdout) -> None:
    """One line per injectable point: ``name<TAB>description``."""
    for name in sorted(FAULT_POINTS):
        print(f"{name}\t{FAULT_POINTS[name]}", file=file)


def _parse_rejoin(spec: str):
    """``HOST@GEN[,HOST@GEN...]`` -> [(host, generation), ...]."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            host_s, gen_s = part.split("@")
            out.append((int(host_s), int(gen_s)))
        except ValueError:
            raise SystemExit(
                f"bad --rejoin spec {part!r}: expected HOST@GENERATION "
                f"(e.g. 1@1: host 1 announces a join while generation 1 "
                f"runs)") from None
    return out


def _get_json(url: str, path: str, timeout: float = 10.0) -> dict:
    with urllib.request.urlopen(url + path, timeout=timeout) as resp:
        return json.loads(resp.read())


def _post_json(url: str, path: str, payload: dict,
               timeout: float = 120.0) -> dict:
    req = urllib.request.Request(
        url + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _say(msg: str) -> None:
    print(f"chaos: {msg}", file=sys.stderr, flush=True)


def _serve_env(args) -> dict:
    """Environment for a serve-twin subprocess (CPU device forcing +
    unbuffered + repo on path)."""
    env = dict(os.environ)
    if args.cpu_devices:
        env["JAX_PLATFORMS"] = "cpu"
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       env.get("XLA_FLAGS", "")).strip()
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_"
                            f"count={args.cpu_devices}").strip()
    env["PYTHONUNBUFFERED"] = "1"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _boot_serve(env: dict, flags: list, timeout: float,
                ckpt_dir: str = None, port: int = 0):
    """Boot one `tpu-mnist serve` subprocess on a fresh-init checkpoint
    dir; returns ``(server, log, ckpt_dir, url)`` (url None = never came
    up; caller prints the log tail and bails). Caller owns teardown.
    ``ckpt_dir``/``port`` let the fleet twins RESTART a killed backend
    on its old port with its old checkpoints (the re-admission leg)."""
    if ckpt_dir is None:
        ckpt_dir = tempfile.mkdtemp(prefix="tpumnist-serve-chaos-")
    log = tempfile.NamedTemporaryFile(mode="w+", suffix=".log",
                                      delete=False)
    cmd = [sys.executable, "-m", "pytorch_distributed_mnist_tpu", "serve",
           "--checkpoint-dir", ckpt_dir, "--host", "127.0.0.1",
           "--port", str(port)] + flags
    _say(f"booting serve twin: {' '.join(cmd)}")
    server = subprocess.Popen(cmd, env=env, stdout=log,
                              stderr=subprocess.STDOUT)
    url = None
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline and url is None:
        if server.poll() is not None:
            break
        log.flush()
        with open(log.name) as f:
            m = re.search(r"serving on (http://\S+)", f.read())
        if m:
            url = m.group(1).rstrip("/")
        else:
            time.sleep(0.2)
    if url is None:
        with open(log.name) as f:
            print(f.read()[-4000:], file=sys.stderr)
        _say("server never came up")
    return server, log, ckpt_dir, url


def _kill_serve(server, log, ckpt_dir) -> None:
    server.kill()
    server.wait()
    log.close()
    os.unlink(log.name)
    shutil.rmtree(ckpt_dir, ignore_errors=True)


def _boot_router(env: dict, backend_urls: list, timeout: float,
                 extra_flags: list = ()):
    """Boot one `tpu-mnist route` subprocess over the given backends;
    returns ``(router, log, url)`` (url None = never came up). Tight
    health cadence on purpose: the twins want quarantine/probation
    transitions inside their wall-clock budget, not production's."""
    log = tempfile.NamedTemporaryFile(mode="w+", suffix=".log",
                                      delete=False)
    cmd = [sys.executable, "-m", "pytorch_distributed_mnist_tpu", "route",
           "--backends", ",".join(u.split("//")[-1] for u in backend_urls),
           "--host", "127.0.0.1", "--port", "0",
           "--health-interval", "0.2", "--quarantine-after", "2",
           "--probation-successes", "2",
           "--connect-timeout", "2.0"] + list(extra_flags)
    _say(f"booting router: {' '.join(cmd)}")
    router = subprocess.Popen(cmd, env=env, stdout=log,
                              stderr=subprocess.STDOUT)
    url = None
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline and url is None:
        if router.poll() is not None:
            break
        log.flush()
        with open(log.name) as f:
            m = re.search(r"routing on (http://\S+)", f.read())
        if m:
            url = m.group(1).rstrip("/")
        else:
            time.sleep(0.2)
    if url is None:
        with open(log.name) as f:
            print(f.read()[-4000:], file=sys.stderr)
        _say("router never came up")
    return router, log, url


def _communicate_reaped(proc: subprocess.Popen, timeout: float):
    """``communicate()`` that cannot orphan: on a timeout expiry — or
    any other failure — the child is killed and waited before the error
    propagates. The original shape reaped only on the happy path, and a
    ``TimeoutExpired`` left an orphan loadgen hammering a server the
    twin was about to kill (the PR 10 incident; thread-lifecycle pins
    this)."""
    try:
        return proc.communicate(timeout=timeout)
    except BaseException:
        proc.kill()
        proc.wait()
        raise


def _loadgen_report(proc_out: str) -> dict:
    line = proc_out.strip().splitlines()[-1] if proc_out.strip() else "{}"
    print(line)
    return json.loads(line)


def _sends(report: dict) -> int:
    """Requests the loadgen actually launched: every status code plus
    transport errors (open-loop sends it could not launch count there
    too — nothing is silently skipped)."""
    return (sum(report.get("status_counts", {}).values())
            + report.get("transport_errors", 0))


def run_autoscale_spike(args) -> int:
    """The autoscaler twin (ISSUE 15): spike load must trigger a
    scale-up — FIRST proven in dry-run (the decision log fills, the
    topology does NOT move), THEN for real (the pool resizes up under
    the spike and back down after it, with zero dropped in-flight
    requests). Two server boots on purpose: the dry-run assertion is
    worthless if the same process already resized."""
    env = _serve_env(args)
    # cnn by default: its CPU forward is slow enough that an 8x spike
    # genuinely backs the queue up (linear answers 500 rps from one
    # device — nothing to scale for). --stats-window-s 5 so the
    # controller's p95 reflects the LAST seconds, not the whole run —
    # the post-spike calm must become visible within the twin's budget.
    model = args.serve_model if args.serve_model != "linear" else "cnn"
    # Buckets capped at 4: micro-batching otherwise absorbs an 8x spike
    # whole (a bucket-32 cnn batch amortizes to ~500 rps/device) and
    # there is nothing to scale for. With the cap, the spike genuinely
    # backs the queue up, so the breach fires on BOTH signals — queue
    # depth immediately, window p95 a beat later.
    base_flags = [
        "--model", model, "--buckets", "1,4",
        "--serve-devices", "1", "--max-inflight", "2",
        "--max-wait-ms", "2", "--max-queue", "64",
        "--poll-interval", "5", "--stats-window-s", "5",
        "--autoscale", "--slo-p95-ms", str(args.slo_p95_ms),
        "--autoscale-interval-s", "0.3",
        "--autoscale-cooldown-s", "1.5",
        "--autoscale-down-after", "3",
        "--autoscale-max-devices", "2",
    ]
    loadgen_spike = [
        sys.executable, os.path.join(_REPO, "tools", "loadgen.py"),
        "--mode", "open", "--shape", "spike", "--rate",
        str(args.spike_rate), "--spike-mult", "8",
        "--duration", str(args.spike_duration),
        "--mix", "interactive=0.6,batch=0.3,best_effort=0.1",
        "--timeout", "30"]

    # -- phase 1: dry run. The controller must DECIDE to scale up and
    # must NOT actuate.
    server, log, ckpt_dir, url = _boot_serve(
        env, base_flags + ["--autoscale-dry-run"], args.timeout)
    try:
        if url is None:
            return 1
        lg = subprocess.Popen(loadgen_spike + ["--url", url],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
        out, _ = _communicate_reaped(lg, args.timeout)
        _loadgen_report(out)
        stats = _get_json(url, "/stats")
        scaler = stats.get("autoscaler") or {}
        ups = [d for d in scaler.get("decisions", [])
               if d.get("action") == "scale_up"]
        if not ups or not all(d.get("dry_run") for d in ups):
            _say(f"dry run: expected recorded scale_up decisions, got "
                 f"{scaler.get('decisions')}")
            return 1
        if stats.get("serve_devices") != 1:
            _say(f"dry run actuated! serve_devices="
                 f"{stats.get('serve_devices')}")
            return 1
        _say(f"dry run: {len(ups)} scale_up decision(s) recorded, "
             f"topology untouched (serve_devices=1)")
    finally:
        _kill_serve(server, log, ckpt_dir)

    # -- phase 2: real. The spike must resize the pool up; the calm
    # after it must bring it back down; every accepted request answers.
    server, log, ckpt_dir, url = _boot_serve(env, base_flags,
                                             args.timeout)
    try:
        if url is None:
            return 1
        lg = subprocess.Popen(loadgen_spike + ["--url", url],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
        scaled_up = False
        deadline = time.monotonic() + args.spike_duration + 30
        while time.monotonic() < deadline and lg.poll() is None:
            try:
                stats = _get_json(url, "/stats", timeout=5.0)
            except Exception:  # noqa: BLE001 - server busy; retry
                time.sleep(0.3)
                continue
            if stats.get("serve_devices", 1) > 1:
                scaled_up = True
                break
            time.sleep(0.3)
        out, _ = _communicate_reaped(lg, args.timeout)
        report = _loadgen_report(out)
        if not scaled_up:
            stats = _get_json(url, "/stats")
            scaled_up = (stats.get("autoscaler", {})
                         .get("scale_ups", 0)) > 0
        if not scaled_up:
            _say("spike never scaled the pool up")
            return 1
        if report.get("transport_errors"):
            _say(f"{report['transport_errors']} transport errors — "
                 f"dropped in-flight requests during resize")
            return 1
        answered = report.get("ok", 0) + report.get("rejected", 0) \
            + report.get("quota_rejected", 0)
        if answered != _sends(report):
            _say(f"{_sends(report) - answered} request(s) unanswered")
            return 1
        # Post-spike calm: the controller must scale back DOWN.
        deadline = time.monotonic() + 30
        scaled_down = False
        while time.monotonic() < deadline:
            stats = _get_json(url, "/stats")
            if stats.get("serve_devices") == 1 and \
                    stats.get("autoscaler", {}).get("scale_downs", 0):
                scaled_down = True
                break
            time.sleep(0.5)
        if not scaled_down:
            _say("pool never scaled back down after the spike")
            return 1
        stats = _get_json(url, "/stats")
        scaler = stats["autoscaler"]
        _say(f"autoscale spike twin: {scaler['scale_ups']} up / "
             f"{scaler['scale_downs']} down, zero dropped requests "
             f"({report['ok']} ok, {report['rejected']} shed with "
             f"Retry-After on {report['retry_after_seen']})")
        return 0
    finally:
        _kill_serve(server, log, ckpt_dir)


def run_quota_abuse(args) -> int:
    """The per-client quota twin (ISSUE 15): one hot client hammering
    far past --quota-rps must be clipped with 429s while the
    well-behaved clients' goodput stays >= 90% of their offered load —
    one abuser cannot starve the rest."""
    env = _serve_env(args)
    flags = [
        "--model", args.serve_model, "--buckets", "1,8,32",
        "--serve-devices", str(args.serve_devices),
        "--max-wait-ms", "2", "--max-queue", "64",
        "--poll-interval", "5",
        "--quota-rps", str(args.quota_rps),
    ]
    server, log, ckpt_dir, url = _boot_serve(env, flags, args.timeout)
    try:
        if url is None:
            return 1
        good_rate = max(2.0, args.quota_rps / 4.0)
        duration = args.quota_duration
        hog = subprocess.Popen(
            [sys.executable, os.path.join(_REPO, "tools", "loadgen.py"),
             "--url", url, "--mode", "open", "--rate",
             str(args.quota_rps * 10), "--duration", str(duration),
             "--client-id", "hog", "--timeout", "20"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        good = subprocess.Popen(
            [sys.executable, os.path.join(_REPO, "tools", "loadgen.py"),
             "--url", url, "--mode", "open", "--rate", str(good_rate),
             "--duration", str(duration), "--client-id", "good",
             "--timeout", "20"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        hog_out, _ = _communicate_reaped(hog, args.timeout)
        good_out, _ = _communicate_reaped(good, args.timeout)
        hog_report = _loadgen_report(hog_out)
        good_report = _loadgen_report(good_out)
        if not hog_report.get("quota_rejected"):
            _say("the hot client was never 429'd — quotas inactive?")
            return 1
        if not hog_report.get("retry_after_seen"):
            _say("429s arrived without Retry-After")
            return 1
        good_sends = _sends(good_report)
        good_ok = good_report.get("ok", 0)
        if good_sends == 0 or good_ok < 0.9 * good_sends:
            _say(f"well-behaved client starved: {good_ok}/{good_sends} "
                 f"answered (need >= 90%)")
            return 1
        stats = _get_json(url, "/stats")
        _say(f"quota twin: hog clipped "
             f"({hog_report['quota_rejected']} x 429 of "
             f"{_sends(hog_report)} sends), good client "
             f"{good_ok}/{good_sends} "
             f"({100.0 * good_ok / good_sends:.1f}% goodput); server "
             f"tracked {stats.get('quota', {}).get('clients_tracked')} "
             f"client(s)")
        return 0
    finally:
        _kill_serve(server, log, ckpt_dir)


def _post_predict(url: str, body: bytes, timeout: float = 30.0):
    """POST one pre-serialized /predict body; returns ``(reply_dict,
    x_cache)`` where x_cache is the reply's X-Cache header verdict
    (hit/miss/None) — the cache-storm twin's staleness probe."""
    req = urllib.request.Request(
        url + "/predict", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read()), resp.headers.get("X-Cache")


def run_cache_storm(args) -> int:
    """The response-cache invalidation twin (ISSUE 19): duplicate-heavy
    loadgen (Zipf-shaped key reuse, the cache's best case) over a LIVE
    hot reload. The bar: zero dropped requests through the swap, and
    zero stale logits after it — every post-swap reply must carry the
    new model epoch, because the swap hook bumps the cache generation
    atomically with the param install (an entry from the old params can
    never be replayed as the new model's answer)."""
    env = _serve_env(args)
    flags = ["--model", "linear", "--buckets", "1,8",
             "--serve-devices", str(args.serve_devices),
             "--max-wait-ms", "2", "--poll-interval", "0.2"]
    server, log, ckpt_dir, url = _boot_serve(env, flags, args.timeout)
    try:
        if url is None:
            return 1
        # One fixed duplicate body — the probe key the whole twin
        # replays (deterministic, so pre- and post-swap probes are
        # byte-identical and MUST collide in the cache).
        rng = random.Random(3)
        probe = json.dumps({"images": [
            [[rng.randrange(256) for _ in range(28)]
             for _ in range(28)]]}).encode()
        pre_epochs, pre_cache = set(), []
        for _ in range(3):
            reply, verdict = _post_predict(url, probe)
            pre_epochs.add(reply.get("model_epoch"))
            pre_cache.append(verdict)
        if len(pre_epochs) != 1:
            _say(f"pre-swap epochs disagree: {sorted(pre_epochs)}")
            return 1
        if "hit" not in pre_cache:
            _say(f"duplicate probe never hit the cache ({pre_cache}) — "
                 f"cache inactive?")
            return 1
        (old_epoch,) = pre_epochs
        _say(f"cache warm on epoch {old_epoch} ({pre_cache})")

        # The storm: Zipf-duplicate loadgen riding THROUGH the reload —
        # open-loop over a fixed duration (a closed burst would finish
        # before the publish subprocess even imports jax, and "zero
        # drops through the swap" would be vacuous).
        storm_s = 10.0
        storm = subprocess.Popen(
            [sys.executable, os.path.join(_REPO, "tools", "loadgen.py"),
             "--url", url, "--mode", "open",
             "--rate", str(max(20.0, args.requests / storm_s)),
             "--duration", str(storm_s), "--shape", "zipf:1.1",
             "--timeout", "20"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        time.sleep(0.5)  # let the storm get in flight first
        new_epoch = (old_epoch or 0) + 7
        _seed_checkpoint(env, ckpt_dir, new_epoch)
        _say(f"published checkpoint_{new_epoch}.npz under the storm")
        deadline = time.monotonic() + args.timeout
        epoch = None
        while time.monotonic() < deadline:
            epoch = _get_json(url, "/healthz").get("model_epoch")
            if epoch == new_epoch:
                break
            time.sleep(0.2)
        if epoch != new_epoch:
            _say(f"hot reload never landed (model_epoch={epoch}, want "
                 f"{new_epoch})")
            return 1
        out, _ = _communicate_reaped(storm, args.timeout)
        report = _loadgen_report(out)
        sends = _sends(report)
        dropped = (report.get("transport_errors", 0)
                   + report.get("conn_refused", 0))
        if dropped or report.get("ok", 0) != sends:
            _say(f"storm dropped requests through the swap: "
                 f"{report.get('ok', 0)}/{sends} answered 200, "
                 f"{dropped} transport failures")
            return 1
        hits = report.get("cache_client", {}).get("hits", 0)
        if not hits:
            _say("the storm never observed a cache hit — the Zipf "
                 "duplicates missed the cache?")
            return 1

        # Staleness probe: the SAME bytes that were cached pre-swap.
        # Every reply must now carry the new epoch — a single old-epoch
        # reply is a stale logit replay, the exact bug the generation
        # bump exists to make impossible.
        post_cache = []
        for i in range(8):
            reply, verdict = _post_predict(url, probe)
            post_cache.append(verdict)
            if reply.get("model_epoch") != new_epoch:
                _say(f"STALE reply {i}: model_epoch="
                     f"{reply.get('model_epoch')} after swap to "
                     f"{new_epoch} (X-Cache: {verdict})")
                return 1
        if "hit" not in post_cache:
            _say(f"post-swap probe never re-cached ({post_cache})")
            return 1
        stats = _get_json(url, "/stats")
        cache_stats = stats.get("cache", {})
        _say(f"cache storm: {report['ok']}/{sends} answered through the "
             f"reload ({hits} client-observed hits), zero stale replies "
             f"after the swap to epoch {new_epoch} (cache generation "
             f"{cache_stats.get('generation')}, "
             f"{cache_stats.get('stale_drops')} stale insert(s) "
             f"dropped)")
        return 0
    finally:
        _kill_serve(server, log, ckpt_dir)


def run_serve_chaos(args) -> int:
    """The serve-plane twins: boot a REAL serve subprocess, hammer it
    with loadgen, and either sabotage a mesh group (``--serve-fault``:
    the pool must quarantine, fail requests over, and regroup under the
    live traffic) or roll the topology (``--resize``: each /resize must
    complete with zero dropped requests). Success = every loadgen
    request answered 200 AND the final /stats topology matches
    ``--expect-groups``."""
    env = dict(os.environ)
    if args.serve_fault:
        env[SERVE_FAULT_ENV] = args.serve_fault
    else:
        env.pop(SERVE_FAULT_ENV, None)
    if args.canary_rollback:
        # Rehearse the rollback-under-traffic scenario: every shadow
        # comparison is injected to disagree, so the canary must roll
        # back while loadgen hammers — and still answer EVERY request
        # from the baseline (zero drops is the twin's bar).
        env[CANARY_FAULT_ENV] = "disagree"
    else:
        env.pop(CANARY_FAULT_ENV, None)
    if args.cpu_devices:
        env["JAX_PLATFORMS"] = "cpu"
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       env.get("XLA_FLAGS", "")).strip()
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_"
                            f"count={args.cpu_devices}").strip()
    env["PYTHONUNBUFFERED"] = "1"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")

    ckpt_dir = tempfile.mkdtemp(prefix="tpumnist-serve-chaos-")
    log = tempfile.NamedTemporaryFile(
        mode="w+", suffix=".log", delete=False)
    cmd = [sys.executable, "-m", "pytorch_distributed_mnist_tpu", "serve",
           "--checkpoint-dir", ckpt_dir, "--model", args.serve_model,
           "--host", "127.0.0.1", "--port", "0", "--buckets", "1,8,32",
           "--serve-devices", str(args.serve_devices),
           "--serve-mode", args.serve_mode,
           "--quarantine-after", str(args.quarantine_after),
           "--max-wait-ms", "2", "--poll-interval", "1"]
    if args.serve_mesh:
        cmd += ["--serve-mesh", str(args.serve_mesh)]
    serve_precision = args.serve_precision
    if args.canary_rollback and not serve_precision:
        serve_precision = "bf16"  # the canary needs a quantized plane
    if serve_precision:
        cmd += ["--serve-precision", serve_precision]
    if args.canary_rollback:
        # Fraction 1.0 shadows every batch; a huge promotion window and
        # a zero budget make the injected disagreement the only
        # possible transition.
        cmd += ["--canary-fraction", "1.0",
                "--canary-promote-after", "100000",
                "--canary-budget", "0.0"]
    _say(f"booting serve twin: {' '.join(cmd)}"
         + (f" [{SERVE_FAULT_ENV}={args.serve_fault}]"
            if args.serve_fault else ""))
    server = subprocess.Popen(cmd, env=env, stdout=log,
                              stderr=subprocess.STDOUT)
    loadgen = None
    url = None
    try:
        deadline = time.monotonic() + args.timeout
        while time.monotonic() < deadline and url is None:
            if server.poll() is not None:
                break
            log.flush()
            with open(log.name) as f:
                m = re.search(r"serving on (http://\S+)", f.read())
            if m:
                url = m.group(1).rstrip("/")
            else:
                time.sleep(0.2)
        if url is None:
            with open(log.name) as f:
                print(f.read()[-4000:], file=sys.stderr)
            _say("server never came up")
            return 1
        _say(f"server up at {url}")

        loadgen_cmd = [
            sys.executable, os.path.join(_REPO, "tools", "loadgen.py"),
            "--smoke", "--url", url, "--requests", str(args.requests),
            "--concurrency", "8"]
        loadgen = subprocess.Popen(loadgen_cmd, stdout=subprocess.PIPE,
                                   stderr=subprocess.STDOUT, text=True)
        # Roll the topology WHILE the load runs: each /resize must
        # complete under traffic with zero dropped requests.
        for target in args.resize_targets:
            time.sleep(0.5)
            reply = _post_json(url, "/resize", {"serve_devices": target})
            _say(f"/resize -> {target} replicas: topology generation "
                 f"{reply['new']['topology_generation']}")
        out, _ = _communicate_reaped(loadgen, args.timeout)
        loadgen_rc = loadgen.returncode
        loadgen = None  # reaped; nothing left for the finally to kill
        report_line = out.strip().splitlines()[-1] if out.strip() else "{}"
        print(report_line)
        report = json.loads(report_line)
        if loadgen_rc != 0 or report.get("ok") != args.requests:
            _say(f"loadgen dropped/failed requests (rc="
                 f"{loadgen_rc}, ok={report.get('ok')}/"
                 f"{args.requests})")
            return 1
        _say(f"loadgen: {args.requests}/{args.requests} answered, zero "
             f"drops")

        if args.canary_rollback:
            # The injected disagreement must have rolled the publish
            # back — with the baseline still answering everything.
            stats = _get_json(url, "/stats")
            can = stats.get("canary") or {}
            if can.get("state") != "rolled_back":
                _say(f"expected canary state rolled_back under injected "
                     f"disagreement, got {can.get('state')!r}")
                return 1
            _say(f"canary rolled back ({can.get('disagreed_rows')} "
                 f"disagreeing rows of {can.get('compared_rows')} "
                 f"compared); baseline kept serving, zero drops")

        # Wait for the pool to finish healing (quarantine -> regroup),
        # then assert the final topology with the loadgen smoke gate.
        deadline = time.monotonic() + args.timeout
        while time.monotonic() < deadline:
            stats = _get_json(url, "/stats")
            if not stats.get("quarantined_groups"):
                break
            time.sleep(0.5)
        final = [sys.executable, os.path.join(_REPO, "tools",
                                              "loadgen.py"),
                 "--smoke", "--url", url, "--requests", "50",
                 "--concurrency", "4"]
        if args.expect_groups:
            final += ["--expect-groups", str(args.expect_groups)]
        proc = subprocess.run(final, capture_output=True, text=True,
                              timeout=args.timeout)
        print(proc.stdout.strip().splitlines()[-1]
              if proc.stdout.strip() else "{}")
        if proc.returncode != 0:
            _say("post-heal topology smoke failed")
            return 1
        stats = _get_json(url, "/stats")
        _say(f"final topology: generation "
             f"{stats.get('topology_generation')}, "
             f"{stats.get('active_groups')}/{stats.get('groups')} "
             f"groups active, regroups={stats.get('regroups')}, "
             f"failovers={stats.get('failovers')}")
        if args.serve_fault and not stats.get("regroups"):
            _say("expected at least one regroup under --serve-fault")
            return 1
        return 0
    finally:
        # A failed /resize (HTTPError) or a loadgen communicate timeout
        # propagates through here with loadgen still running against a
        # server this block is about to kill: reap it too, or it spins
        # connection errors as an orphan.
        if loadgen is not None and loadgen.poll() is None:
            loadgen.kill()
            loadgen.wait()
        server.kill()
        server.wait()
        log.close()
        os.unlink(log.name)
        shutil.rmtree(ckpt_dir, ignore_errors=True)


# Delta-publish helper run in a subprocess (chaos stays jax-free).
# Deterministic per epoch: the state is base(seed 7) with the SMALLEST
# params leaf (the bias) shifted by e*1e-3, so adjacent epochs differ in
# exactly one leaf and re-running any epoch reproduces its bytes.
# argv: directory e0 n drop_new sleep_s. drop_new=1 sabotages the
# publish by deleting every chunk it newly added — the missing-chunk
# torn-publish twin.
_DELTA_PUBLISH_CODE = """
import os, sys, time
import jax, jax.numpy as jnp
from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from pytorch_distributed_mnist_tpu.distrib.cas import ChunkStore
from pytorch_distributed_mnist_tpu.distrib.publish import publish_state

directory, e0, n = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
drop_new, sleep_s = sys.argv[4] == "1", float(sys.argv[5])
m = get_model("linear", compute_dtype=jnp.float32)
base = create_train_state(m, jax.random.key(7))
store = ChunkStore(directory)
leaves, treedef = jax.tree_util.tree_flatten(base.params)
small = min(range(len(leaves)), key=lambda j: leaves[j].size)
for e in range(e0, e0 + n):
    shifted = list(leaves)
    shifted[small] = leaves[small] + e * 1e-3
    state = base.replace(
        params=jax.tree_util.tree_unflatten(treedef, shifted))
    before = store.digests()
    publish_state(state, epoch=e, best_acc=0.5, directory=directory,
                  process_index=0)
    if drop_new:
        for digest in store.digests() - before:
            os.remove(store.path(digest))
    if sleep_s and e + 1 < e0 + n:
        time.sleep(sleep_s)
"""


def _delta_publish_epochs(env: dict, directory: str, e0: int, n: int,
                          drop_new: bool = False,
                          sleep_s: float = 0.0) -> None:
    subprocess.run(
        [sys.executable, "-c", _DELTA_PUBLISH_CODE, directory, str(e0),
         str(n), "1" if drop_new else "0", str(sleep_s)],
        env=env, check=True, timeout=600)


def _chunks_bytes(directory: str) -> int:
    chunk_dir = os.path.join(directory, "chunks")
    if not os.path.isdir(chunk_dir):
        return 0
    return sum(os.path.getsize(os.path.join(chunk_dir, name))
               for name in os.listdir(chunk_dir))


def _seed_checkpoint(env: dict, directory: str, epoch: int) -> str:
    """Save a real linear-model checkpoint_{epoch}.npz into
    ``directory`` via a subprocess (chaos itself stays jax-import-free)
    and return its path."""
    code = (
        "import sys, jax, jax.numpy as jnp\n"
        "from pytorch_distributed_mnist_tpu.models import get_model\n"
        "from pytorch_distributed_mnist_tpu.train.state import "
        "create_train_state\n"
        "from pytorch_distributed_mnist_tpu.train.checkpoint import "
        "save_checkpoint\n"
        "m = get_model('linear', compute_dtype=jnp.float32)\n"
        "s = create_train_state(m, jax.random.key(7))\n"
        "save_checkpoint(s, epoch=int(sys.argv[2]), best_acc=0.5,\n"
        "                is_best=False, directory=sys.argv[1],\n"
        "                process_index=0)\n")
    subprocess.run([sys.executable, "-c", code, directory, str(epoch)],
                   env=env, check=True, timeout=300)
    return os.path.join(directory, f"checkpoint_{epoch}.npz")


def run_fleet_chaos(args) -> int:
    """The fleet-federation twins (ISSUE 17): a REAL router subprocess
    over --fleet N real single-chip serve subprocesses.

    --kill-backend K: SIGKILL backend K mid-loadgen; every request must
    still be answered (router failover + the loadgen's bounded
    --retry-transport = zero DROPPED), the corpse must quarantine, and
    a restart on its old port must walk probation back to healthy.

    --rolling-reload: POST /rollout publishes a new epoch to the whole
    fleet one backend at a time under live loadgen — zero drops, every
    backend on the new epoch afterward.

    --fleet-canary-rollback: publish behind a fleet canary with
    TPUMNIST_FLEET_FAULT=canary_disagree injected into the router —
    the canary must roll back (baseline weights republished) while
    every request is still answered.

    --delta-publish E (ISSUE 18): every backend watches ONE shared
    checkpoint directory; E delta publishes land under live loadgen —
    zero drops, every backend converges to the last epoch, and the
    chunk bytes each adjacent publish adds must be a small fraction of
    the cold (whole-state) bytes."""
    env = _serve_env(args)
    router_env = dict(env)
    if args.fleet_canary_rollback:
        router_env[FLEET_FAULT_ENV] = "canary_disagree"
    else:
        router_env.pop(FLEET_FAULT_ENV, None)
    backend_flags = ["--model", "linear", "--buckets", "1,8",
                     "--max-wait-ms", "2", "--max-queue", "256",
                     "--poll-interval", "0.2"]
    backends = []  # (server, log, ckpt_dir, url)
    router = router_log = None
    staging = tempfile.mkdtemp(prefix="tpumnist-fleet-staging-")
    shared_dir = None
    try:
        if args.delta_publish:
            # One directory for the whole fleet (the shared-fs
            # scenario); seeded with a COLD delta publish so the
            # backends boot serving epoch 1 off the manifest and the
            # store holds the full-state baseline bytes to compare
            # adjacent publishes against.
            shared_dir = tempfile.mkdtemp(prefix="tpumnist-fleet-delta-")
            _delta_publish_epochs(env, shared_dir, 1, 1)
            _say("seeded epoch-1 delta publish (cold store)")
        for i in range(args.fleet):
            server, log, ckpt_dir, url = _boot_serve(
                env, backend_flags, args.timeout, ckpt_dir=shared_dir)
            if url is None:
                return 1
            backends.append([server, log, ckpt_dir, url])
        _say(f"fleet up: {[b[3] for b in backends]}")
        router, router_log, url = _boot_router(
            router_env, [b[3] for b in backends], args.timeout)
        if url is None:
            return 1
        deadline = time.monotonic() + args.timeout
        while time.monotonic() < deadline:
            if _get_json(url, "/healthz").get("routable") == args.fleet:
                break
            time.sleep(0.2)
        _say(f"router up at {url}, {args.fleet} backends routable")
        dirs_body = {b[3].split("//")[-1]: b[2] for b in backends}

        if args.kill_backend is not None:
            victim = backends[args.kill_backend]
            duration = 6.0
            loadgen = subprocess.Popen(
                [sys.executable, os.path.join(_REPO, "tools",
                                              "loadgen.py"),
                 "--mode", "open", "--rate", "80",
                 "--duration", str(duration), "--retry-transport", "2",
                 "--url", url],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True)
            time.sleep(duration * 0.35)
            _say(f"SIGKILL backend {args.kill_backend} ({victim[3]})")
            victim[0].kill()
            victim[0].wait()
            out, _ = _communicate_reaped(loadgen, args.timeout)
            report = _loadgen_report(out)
            answered = sum(report.get("status_counts", {}).values())
            dropped = (report.get("transport_errors", 0)
                       + report.get("conn_refused", 0))
            if loadgen.returncode != 0 or dropped or \
                    report.get("ok") != answered or answered < 100:
                _say(f"DROPPED requests through the kill: ok="
                     f"{report.get('ok')}/{answered}, dropped={dropped}")
                return 1
            _say(f"{answered} requests answered through the kill, zero "
                 f"dropped ({report.get('transport_retries')} client "
                 f"retries)")
            stats = _get_json(url, "/stats")
            victim_name = victim[3].split("//")[-1]
            rows = {r["name"]: r for r in stats["backends"]}
            if rows[victim_name]["state"] != "quarantined" or \
                    not stats["fleet"]["failovers"]:
                _say(f"expected quarantine+failover, got state="
                     f"{rows[victim_name]['state']}, failovers="
                     f"{stats['fleet']['failovers']}")
                return 1
            _say(f"victim quarantined; failovers="
                 f"{stats['fleet']['failovers']}, merged fleet p99="
                 f"{stats['fleet']['window']['p99_ms']}ms")
            # Restart on the old port: probation -> healthy, no
            # operator action at the router.
            port = int(victim[3].rsplit(":", 1)[1])
            victim[1].close()
            os.unlink(victim[1].name)
            server, log, ckpt_dir, burl = _boot_serve(
                env, backend_flags, args.timeout,
                ckpt_dir=victim[2], port=port)
            victim[0], victim[1], victim[3] = server, log, burl or ""
            if burl is None:
                return 1
            deadline = time.monotonic() + args.timeout
            row = {}
            while time.monotonic() < deadline:
                stats = _get_json(url, "/stats")
                row = {r["name"]: r
                       for r in stats["backends"]}[victim_name]
                if row["state"] == "healthy":
                    break
                time.sleep(0.2)
            if row.get("state") != "healthy" or not row.get("readmissions"):
                _say(f"victim never re-admitted: {row}")
                return 1
            _say(f"victim re-admitted through probation "
                 f"(readmissions={row['readmissions']}); fleet whole "
                 f"again")
            return 0

        if args.rolling_reload:
            source = _seed_checkpoint(env, staging, epoch=1)
            loadgen = subprocess.Popen(
                [sys.executable, os.path.join(_REPO, "tools",
                                              "loadgen.py"),
                 "--mode", "open", "--rate", "60", "--duration", "8",
                 "--retry-transport", "2", "--url", url],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True)
            time.sleep(1.0)
            reply = _post_json(url, "/rollout",
                               {"source": source, "dirs": dirs_body})
            if not reply.get("ok") or \
                    len(reply.get("updated", [])) != args.fleet:
                _say(f"rolling reload failed: {reply}")
                return 1
            _say(f"rolled epoch 1 onto {reply['updated']}")
            out, _ = _communicate_reaped(loadgen, args.timeout)
            report = _loadgen_report(out)
            answered = sum(report.get("status_counts", {}).values())
            dropped = (report.get("transport_errors", 0)
                       + report.get("conn_refused", 0))
            if loadgen.returncode != 0 or dropped or \
                    report.get("ok") != answered:
                _say(f"DROPPED requests through the rollout: ok="
                     f"{report.get('ok')}/{answered}, dropped={dropped}")
                return 1
            for _, _, _, burl in backends:
                health = _get_json(burl, "/healthz")
                if health.get("model_epoch") != 1 or health.get("draining"):
                    _say(f"backend {burl} not on epoch 1 post-rollout: "
                         f"{health}")
                    return 1
            _say(f"{answered} requests answered through the fleet-wide "
                 f"publish, zero dropped; every backend on epoch 1")
            return 0

        if args.fleet_canary_rollback:
            # Baseline first: the whole fleet on a real epoch 1, so the
            # rollback has baseline WEIGHTS to restore.
            source = _seed_checkpoint(env, staging, epoch=1)
            reply = _post_json(url, "/rollout",
                               {"source": source, "dirs": dirs_body})
            if not reply.get("ok"):
                _say(f"baseline publish failed: {reply}")
                return 1
            target = _seed_checkpoint(env, staging, epoch=2)
            canary_name = backends[0][3].split("//")[-1]
            reply = _post_json(url, "/rollout", {
                "source": target, "dirs": dirs_body,
                "canary": {"fraction": 1.0, "budget": 0.0,
                           "promote_after": 100000,
                           "backends": [canary_name]}})
            if not reply.get("ok"):
                _say(f"canary publish failed: {reply}")
                return 1
            # client_id puts every request in the (fraction-1.0)
            # cohort; the injected fault disagrees every row, so the
            # FIRST cohort reply must roll the fleet canary back.
            proc = subprocess.run(
                [sys.executable, os.path.join(_REPO, "tools",
                                              "loadgen.py"),
                 "--requests", str(args.requests), "--concurrency", "4",
                 "--retry-transport", "2", "--client-id", "canary-probe",
                 "--url", url],
                capture_output=True, text=True, timeout=args.timeout)
            report = _loadgen_report(proc.stdout)
            answered = sum(report.get("status_counts", {}).values())
            dropped = (report.get("transport_errors", 0)
                       + report.get("conn_refused", 0))
            if dropped or report.get("ok") != answered:
                _say(f"DROPPED requests during the canary: ok="
                     f"{report.get('ok')}/{answered}, dropped={dropped}")
                return 1
            deadline = time.monotonic() + args.timeout
            can = {}
            while time.monotonic() < deadline:
                can = _get_json(url, "/stats").get("fleet_canary") or {}
                if can.get("state") == "rolled_back":
                    break
                time.sleep(0.2)
            if can.get("state") != "rolled_back":
                _say(f"expected fleet canary rolled_back under injected "
                     f"disagreement, got {can.get('state')!r}")
                return 1
            # The rollback republishes the BASELINE weights (as the
            # next epoch number — epochs are publish sequence numbers);
            # wait for the canary backend to swap onto them.
            deadline = time.monotonic() + args.timeout
            epoch = None
            while time.monotonic() < deadline:
                epoch = _get_json(backends[0][3],
                                  "/healthz").get("model_epoch")
                if epoch == 3:
                    break
                time.sleep(0.2)
            if epoch != 3:
                _say(f"canary backend never restored baseline weights "
                     f"(epoch {epoch}, want 3 = baseline republished)")
                return 1
            _say(f"fleet canary rolled back "
                 f"({can.get('disagreed_rows')} disagreeing rows of "
                 f"{can.get('compared_rows')}); baseline weights "
                 f"republished, {answered} requests answered, zero "
                 f"dropped")
            return 0

        if args.delta_publish:
            n = args.delta_publish
            cold = _chunks_bytes(shared_dir)
            last_epoch = 1 + n
            duration = max(8.0, 2.0 * n + 4.0)
            loadgen = subprocess.Popen(
                [sys.executable, os.path.join(_REPO, "tools",
                                              "loadgen.py"),
                 "--mode", "open", "--rate", "60",
                 "--duration", str(duration), "--retry-transport", "2",
                 "--url", url],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True)
            time.sleep(1.0)
            t0 = time.monotonic()
            _delta_publish_epochs(env, shared_dir, 2, n, sleep_s=1.0)
            # Fleet consistency: every backend swaps onto the LAST
            # published epoch while traffic keeps flowing.
            deadline = time.monotonic() + args.timeout
            converged = False
            while time.monotonic() < deadline and not converged:
                converged = all(
                    _get_json(burl, "/healthz").get("model_epoch")
                    == last_epoch for _, _, _, burl in backends)
                if not converged:
                    time.sleep(0.2)
            consistency_s = time.monotonic() - t0
            out, _ = _communicate_reaped(loadgen, args.timeout)
            report = _loadgen_report(out)
            answered = sum(report.get("status_counts", {}).values())
            dropped = (report.get("transport_errors", 0)
                       + report.get("conn_refused", 0))
            if loadgen.returncode != 0 or dropped or \
                    report.get("ok") != answered or answered < 100:
                _say(f"DROPPED requests through the delta publishes: "
                     f"ok={report.get('ok')}/{answered}, "
                     f"dropped={dropped}")
                return 1
            if not converged:
                epochs = [_get_json(burl, "/healthz").get("model_epoch")
                          for _, _, _, burl in backends]
                _say(f"fleet never converged to epoch {last_epoch}: "
                     f"{epochs}")
                return 1
            delta = _chunks_bytes(shared_dir) - cold
            per_publish = delta / n
            _say(f"{n} delta publishes: {per_publish:.0f}B/publish vs "
                 f"{cold}B cold ({100 * per_publish / max(cold, 1):.2f}"
                 f"%); fleet consistent in {consistency_s:.1f}s; "
                 f"{answered} requests answered, zero dropped")
            if per_publish >= 0.30 * cold:
                _say("adjacent delta publishes should move far fewer "
                     "bytes than the cold publish")
                return 1
            return 0

        _say("--fleet needs one of --kill-backend K / --rolling-reload "
             "/ --fleet-canary-rollback / --delta-publish E")
        return 2
    finally:
        if router is not None:
            router.kill()
            router.wait()
        if router_log is not None:
            router_log.close()
            os.unlink(router_log.name)
        for server, log, ckpt_dir, _ in backends:
            _kill_serve(server, log, ckpt_dir)
        shutil.rmtree(staging, ignore_errors=True)


def run_torn_manifest(args) -> int:
    """The torn-publish twin (ISSUE 18): one real serve process on a
    delta-published directory, fed three kinds of publish damage.

    1. A TORN manifest (half a JSON file under the published name —
       a publisher that died mid-write without the tmp+rename
       discipline): content damage, permanent-skip for that file.
    2. A manifest referencing a MISSING chunk (the publish's new chunks
       deleted after the rename): absence for that publish,
       permanent-skip until a newer manifest appears.
    3. A clean publish: the watcher recovers onto it with no restart.

    Through all three the server answers every request on the params it
    has — reload failures are recorded, never served."""
    env = _serve_env(args)
    ckpt_dir = tempfile.mkdtemp(prefix="tpumnist-torn-")
    server = log = None
    try:
        _delta_publish_epochs(env, ckpt_dir, 1, 1)
        server, log, ckpt_dir, url = _boot_serve(
            env, ["--model", "linear", "--buckets", "1,8",
                  "--max-wait-ms", "2", "--max-queue", "256",
                  "--poll-interval", "0.2"],
            args.timeout, ckpt_dir=ckpt_dir)
        if url is None:
            return 1
        if _get_json(url, "/healthz").get("model_epoch") != 1:
            _say("server did not boot onto the epoch-1 manifest")
            return 1
        _say("serving epoch 1 off the seeded manifest")

        def _smoke(stage: str) -> bool:
            proc = subprocess.run(
                [sys.executable, os.path.join(_REPO, "tools",
                                              "loadgen.py"),
                 "--smoke", "--url", url, "--requests", "50",
                 "--concurrency", "4"],
                capture_output=True, text=True, timeout=args.timeout)
            report = _loadgen_report(proc.stdout)
            if proc.returncode != 0 or report.get("ok") != 50:
                _say(f"requests dropped {stage}: {report}")
                return False
            return True

        def _await_failures(want: int) -> bool:
            deadline = time.monotonic() + args.timeout
            while time.monotonic() < deadline:
                if _get_json(url, "/stats").get(
                        "reload_failures", 0) >= want:
                    return True
                time.sleep(0.2)
            _say(f"watcher never recorded reload failure #{want}")
            return False

        # 1: torn JSON under the published epoch-2 name.
        with open(os.path.join(ckpt_dir,
                               "checkpoint_1.manifest"), "rb") as f:
            data = f.read()
        with open(os.path.join(ckpt_dir,
                               "checkpoint_2.manifest"), "wb") as f:
            f.write(data[:len(data) // 2])
        if not _await_failures(1):
            return 1
        if _get_json(url, "/healthz").get("model_epoch") != 1:
            _say("torn manifest must not change the serving params")
            return 1
        if not _smoke("under the torn manifest"):
            return 1
        _say("torn manifest skipped (still serving epoch 1, zero "
             "drops)")

        # 2: epoch-3 manifest whose new chunks were deleted post-rename.
        _delta_publish_epochs(env, ckpt_dir, 3, 1, drop_new=True)
        if not _await_failures(2):
            return 1
        if _get_json(url, "/healthz").get("model_epoch") != 1:
            _say("missing-chunk manifest must not change the serving "
                 "params")
            return 1
        if not _smoke("under the missing-chunk manifest"):
            return 1
        _say("missing-chunk manifest skipped (still serving epoch 1)")

        # 3: the next CLEAN publish recovers with no operator action.
        _delta_publish_epochs(env, ckpt_dir, 4, 1)
        deadline = time.monotonic() + args.timeout
        epoch = None
        while time.monotonic() < deadline:
            epoch = _get_json(url, "/healthz").get("model_epoch")
            if epoch == 4:
                break
            time.sleep(0.2)
        if epoch != 4:
            _say(f"clean publish never recovered the watcher "
                 f"(model_epoch={epoch}, want 4)")
            return 1
        if not _smoke("after the recovery publish"):
            return 1
        stats = _get_json(url, "/stats")
        _say(f"recovered onto epoch 4 (reloads={stats.get('reloads')}, "
             f"reload_failures={stats.get('reload_failures')}); zero "
             f"drops end to end")
        return 0
    finally:
        if server is not None:
            _kill_serve(server, log, ckpt_dir)
        else:
            shutil.rmtree(ckpt_dir, ignore_errors=True)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="chaos",
        description="fault-injection twins for the run-supervision layer",
    )
    p.add_argument("--list", action="store_true",
                   help="enumerate injectable fault points and exit")
    p.add_argument("--fault", type=str, default=None,
                   metavar="POINT:HOST:KIND[:ARG][,...]",
                   help="the fault(s) to inject (see --list; kinds: "
                        "kill, raise, stall; comma-join for multiple, "
                        "e.g. a host loss plus an elastic_rebuild "
                        "sabotage of a survivor). Omit for a clean "
                        "control run")
    p.add_argument("--elastic", action="store_true",
                   help="run under the elastic supervisor "
                        "(runtime/elastic.py): a host loss SHRINKS the "
                        "world — survivors re-exec at the smaller size "
                        "and resume from the last published checkpoint "
                        "— instead of ending the run")
    p.add_argument("--elastic-grow", action="store_true",
                   help="elastic: run the epoch-boundary grow "
                        "rendezvous too, so join records (--rejoin, or "
                        "announce_join) are admitted between epochs — "
                        "the shrink-then-GROW scenarios")
    p.add_argument("--rejoin", type=str, default=None,
                   metavar="HOST@GEN[,...]",
                   help="elastic: write HOST's join record while "
                        "generation GEN runs (the deterministic "
                        "simulation of a returned/replacement host "
                        "announcing itself; e.g. 1@1 for the 2->1->2 "
                        "twin)")
    p.add_argument("--dcn-slices", type=int, default=0, metavar="N",
                   help="run the world on the emulated hierarchical "
                        f"(DCN x ICI) mesh: sets {DCN_SLICES_ENV}=N for "
                        "every rank (N must divide --nprocs; each "
                        "slice is a contiguous block of ranks). The "
                        "slice-loss twins compose this with "
                        "--kill-slice")
    p.add_argument("--kill-slice", type=int, default=None, metavar="S",
                   help="elastic slice-loss twin: SIGKILL EVERY host of "
                        "emulated slice S (mid-epoch, the train_step "
                        "point, skip 5) — the survivors shrink to the "
                        "remaining slice(s), and a world the slice "
                        "count no longer divides lands on the FLAT "
                        "mesh (cli.py's elastic fallback) and resumes "
                        "through the ordinary (W, W') reshard. "
                        "Requires --elastic and --dcn-slices")
    p.add_argument("--min-world", type=int, default=1, metavar="W",
                   help="elastic floor: stop shrinking below W healthy "
                        "hosts (default 1)")
    p.add_argument("--max-world", type=int, default=0, metavar="W",
                   help="elastic ceiling for the grow direction "
                        "(0 = unbounded)")
    p.add_argument("--settle-timeout", type=float, default=60.0,
                   help="elastic: seconds the supervisor waits for the "
                        "remaining ranks to exit once one has failed, "
                        "before killing stragglers and shrinking "
                        "without them (default 60)")
    p.add_argument("--nprocs", type=int, default=2,
                   help="local host processes to spawn (default 2)")
    p.add_argument("--agreement-timeout", type=float, default=15.0,
                   help="watchdog deadline handed to every rank via "
                        f"{TIMEOUT_ENV} (default 15s: chaos runs WANT "
                        "the watchdog — a hang is the bug under test)")
    p.add_argument("--timeout", type=float, default=600.0,
                   help="whole-run wall clock bound before every rank "
                        "is killed (default 600s); for elastic runs, "
                        "the per-generation bound")
    # -- the serve-plane twins (pool self-healing / rolling resize) ----
    p.add_argument("--serve", action="store_true",
                   help="serve-plane chaos: boot a real `tpu-mnist "
                        "serve` subprocess (fresh-init params), hammer "
                        "it with loadgen, and assert zero dropped "
                        "requests through a group 'death' "
                        "(--serve-fault) or a rolling /resize "
                        "(--resize), plus the post-heal topology "
                        "(--expect-groups)")
    p.add_argument("--serve-devices", type=int, default=2,
                   help="serve twin: replicas the server boots with")
    p.add_argument("--serve-mode", type=str, default="replicated",
                   help="serve twin: the data plane to chaos "
                        "(replicated / tensor / expert / pipeline — a "
                        "pipeline group death is a whole-CHAIN "
                        "quarantine + all-stage regroup)")
    p.add_argument("--serve-mesh", type=int, default=0,
                   help="serve twin: chips per mesh group / stages per "
                        "pipeline chain (0 = server default)")
    p.add_argument("--serve-precision", type=str, default=None,
                   help="serve twin: --serve-precision handed to the "
                        "server (f32/bf16/int8w/int8 — the quantized "
                        "serving plane under chaos; defaults to the "
                        "server's f32)")
    p.add_argument("--canary-rollback", action="store_true",
                   help="serve twin: rehearse the shadow-canary "
                        "rollback-under-traffic scenario — boot with "
                        "--canary-fraction 1.0 and an injected "
                        f"disagreement ({CANARY_FAULT_ENV}=disagree), "
                        "assert the canary rolls back while EVERY "
                        "loadgen request is still answered (implies "
                        "--serve-precision bf16 unless given)")
    p.add_argument("--serve-model", type=str, default="linear",
                   help="serve twin: --model for the server (sharded/"
                        "staged modes need their model family, e.g. "
                        "vit for pipeline)")
    p.add_argument("--serve-fault", type=str, default=None,
                   metavar="GROUP[:AFTER]",
                   help=f"serve twin: {SERVE_FAULT_ENV} injection — "
                        "group GROUP's dispatch starts failing after "
                        "AFTER successful batches (its 'chips die'); "
                        "the pool must quarantine it, fail batches "
                        "over, and regroup under traffic")
    p.add_argument("--resize", type=str, default=None, metavar="N1[,N2...]",
                   help="serve twin: roll POST /resize through these "
                        "serve_devices targets while loadgen runs "
                        "(the rolling-topology-change twin)")
    p.add_argument("--expect-groups", type=int, default=0,
                   help="serve twin: require this many ACTIVE groups "
                        "in the final /stats (0 skips)")
    p.add_argument("--autoscale-spike", action="store_true",
                   help="serve twin: the SLO-autoscaler scenario — "
                        "spike loadgen against a 1-device pool with "
                        "--autoscale; phase 1 asserts the DRY-RUN "
                        "decision log (scale_up recorded, topology "
                        "untouched), phase 2 asserts the real resize "
                        "up during the spike and back down after it, "
                        "with zero dropped in-flight requests. "
                        "Needs --cpu-devices >= 2 off-TPU")
    p.add_argument("--slo-p95-ms", type=float, default=150.0,
                   help="autoscale-spike twin: the SLO handed to the "
                        "server — above the calm p95, far below the "
                        "queueing-collapse p95 the spike causes, so "
                        "breach and calm are both unambiguous")
    p.add_argument("--spike-rate", type=float, default=60.0,
                   help="autoscale-spike twin: loadgen base rate "
                        "(burst = 8x through the middle fifth)")
    p.add_argument("--spike-duration", type=float, default=8.0,
                   help="autoscale-spike twin: loadgen run seconds")
    p.add_argument("--quota-abuse", action="store_true",
                   help="serve twin: the per-client quota scenario — "
                        "one hot client at 10x --quota-rps must be "
                        "clipped with 429+Retry-After while a "
                        "well-behaved client keeps >= 90%% goodput")
    p.add_argument("--cache-storm", action="store_true",
                   help="serve twin (ISSUE 19): duplicate-heavy "
                        "(Zipf) loadgen over a LIVE hot reload — "
                        "zero dropped requests through the swap, and "
                        "zero stale logits after it (every post-swap "
                        "reply must carry the new model epoch; the "
                        "swap hook's generation bump is what makes a "
                        "stale replay impossible)")
    p.add_argument("--quota-rps", type=float, default=20.0,
                   help="quota-abuse twin: per-client requests/sec "
                        "handed to the server")
    p.add_argument("--quota-duration", type=float, default=6.0,
                   help="quota-abuse twin: loadgen run seconds")
    p.add_argument("--quarantine-after", type=int, default=3,
                   help="serve twin: consecutive-failure threshold "
                        "handed to the server (default 3)")
    p.add_argument("--requests", type=int, default=400,
                   help="serve twin: loadgen request count (default "
                        "400)")
    p.add_argument("--cpu-devices", type=int, default=0,
                   help="serve twin: force the server onto the CPU "
                        "backend with this many fake devices (local "
                        "rehearsal on accelerator-less boxes; 0 = "
                        "leave the environment alone)")
    # -- the fleet-federation twins (router over N backends) -----------
    p.add_argument("--fleet", type=int, default=0, metavar="N",
                   help="fleet twin: boot a real `tpu-mnist route` "
                        "router over N real single-chip serve "
                        "subprocesses; combine with --kill-backend / "
                        "--rolling-reload / --fleet-canary-rollback")
    p.add_argument("--kill-backend", type=int, default=None,
                   metavar="K",
                   help="fleet twin: SIGKILL backend K mid-loadgen — "
                        "zero DROPPED requests (router failover + "
                        "loadgen --retry-transport), quarantine, then "
                        "probation re-admission after a restart on the "
                        "old port")
    p.add_argument("--rolling-reload", action="store_true",
                   help="fleet twin: POST /rollout a new epoch across "
                        "the whole fleet under live loadgen — zero "
                        "drops, every backend on the new epoch after")
    p.add_argument("--fleet-canary-rollback", action="store_true",
                   help="fleet twin: publish behind a fleet canary "
                        f"with {FLEET_FAULT_ENV}=canary_disagree "
                        "injected into the router — the canary must "
                        "roll back (baseline weights republished) "
                        "while every request is still answered")
    p.add_argument("--delta-publish", type=int, default=0, metavar="E",
                   help="fleet twin (ISSUE 18): all backends watch ONE "
                        "shared checkpoint dir; E adjacent delta "
                        "publishes land under live loadgen — zero "
                        "drops, every backend converges to the last "
                        "epoch, and each publish's new chunk bytes "
                        "must be a small fraction of the cold "
                        "(whole-state) publish")
    p.add_argument("--torn-manifest", action="store_true",
                   help="delta-distribution twin (ISSUE 18): one serve "
                        "process fed a TORN manifest, then a manifest "
                        "with a missing chunk, then a clean publish — "
                        "both damaged publishes are skipped (recorded, "
                        "never served), serving never stops, and the "
                        "clean publish recovers with no restart")
    p.add_argument("cli_args", nargs=argparse.REMAINDER,
                   help="arguments after -- go to tpu-mnist verbatim")
    args = p.parse_args(argv)

    if args.list:
        list_fault_points()
        return 0

    if args.fleet:
        if args.fleet < 2:
            raise SystemExit("--fleet N needs N >= 2 (a 1-backend "
                             "fleet has no failure domain to survive)")
        return run_fleet_chaos(args)
    if args.kill_backend is not None or args.rolling_reload \
            or args.fleet_canary_rollback or args.delta_publish:
        raise SystemExit("--kill-backend/--rolling-reload/"
                         "--fleet-canary-rollback/--delta-publish are "
                         "fleet twins; add --fleet N")
    if args.torn_manifest:
        return run_torn_manifest(args)
    if args.autoscale_spike:
        return run_autoscale_spike(args)
    if args.quota_abuse:
        return run_quota_abuse(args)
    if args.cache_storm:
        return run_cache_storm(args)
    if args.serve:
        args.resize_targets = [int(t) for t in
                               (args.resize or "").split(",") if t.strip()]
        return run_serve_chaos(args)
    if args.resize or args.serve_fault or args.serve_precision \
            or args.canary_rollback:
        raise SystemExit("--serve-fault/--resize/--serve-precision/"
                         "--canary-rollback are serve-plane twins; "
                         "add --serve")

    if args.dcn_slices:
        if args.dcn_slices < 2 or args.nprocs % args.dcn_slices:
            raise SystemExit(
                f"--dcn-slices {args.dcn_slices} must divide --nprocs "
                f"{args.nprocs} into equal slices (>= 2)")
        os.environ[DCN_SLICES_ENV] = str(args.dcn_slices)
    # No flag: an exported TPUMNIST_DCN_SLICES is the documented env
    # contract and stays in force for the workers (unlike FAULT_ENV,
    # which is chaos's own channel and is cleared below when unused).
    if args.kill_slice is not None:
        if not args.elastic or not args.dcn_slices:
            raise SystemExit(
                "--kill-slice is the elastic slice-loss twin; it "
                "requires --elastic and --dcn-slices")
        per = args.nprocs // args.dcn_slices
        if not 0 <= args.kill_slice < args.dcn_slices:
            raise SystemExit(
                f"--kill-slice {args.kill_slice} is not one of the "
                f"{args.dcn_slices} slices")
        specs = [f"train_step:{h}:kill:5"
                 for h in range(args.kill_slice * per,
                                (args.kill_slice + 1) * per)]
        args.fault = ",".join(specs + ([args.fault] if args.fault else []))
    if args.fault:
        parse_fault_specs(args.fault)  # fail fast with the spec's message
        os.environ[FAULT_ENV] = args.fault
    else:
        os.environ.pop(FAULT_ENV, None)
    os.environ[TIMEOUT_ENV] = str(args.agreement_timeout)

    cli_args = list(args.cli_args)
    if cli_args and cli_args[0] == "--":
        cli_args = cli_args[1:]
    print(f"chaos: spawning {args.nprocs} ranks"
          + (" under the elastic supervisor" if args.elastic else "")
          + (f", fault {args.fault}" if args.fault else " (control run)")
          + f", agreement timeout {args.agreement_timeout:g}s",
          file=sys.stderr)
    if args.elastic:
        return supervise(
            args.nprocs, cli_args, min_world=args.min_world,
            max_world=args.max_world, grow=args.elastic_grow,
            rejoin=_parse_rejoin(args.rejoin) if args.rejoin else (),
            settle_timeout=args.settle_timeout,
            generation_timeout=args.timeout)
    if args.elastic_grow or args.rejoin:
        raise SystemExit("--elastic-grow/--rejoin require --elastic")
    return spawn_local(args.nprocs, cli_args, timeout=args.timeout)


if __name__ == "__main__":
    sys.exit(main())
