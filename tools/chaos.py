#!/usr/bin/env python
"""Fault-injection (chaos) harness for the run-supervision subsystem.

Drives the same local N-process world as ``tpu-mnist --spawn`` with ONE
process sabotaged at a named fault point (``runtime/supervision.py``'s
``TPUMNIST_FAULT=point:host:kind[:arg]`` hook), so the agreed-exit
protocol and the collective watchdogs can be exercised against real
process deaths instead of monkeypatches:

    # what can be injected, and where each point fires
    python tools/chaos.py --list

    # SIGKILL host 0 right before the checkpoint publish agreement;
    # host 1 must exit with PeerFailure within the deadline, not hang
    python tools/chaos.py --fault ckpt_publish:0:kill --nprocs 2 \\
        --agreement-timeout 10 -- \\
        --dataset synthetic --model linear --epochs 2 \\
        --optimizer-sharding zero1 --trainer-mode stepwise

    # then prove recovery: the same world, no fault, resumes
    python tools/chaos.py --nprocs 2 -- --dataset synthetic \\
        --model linear --epochs 2 --optimizer-sharding zero1 \\
        --trainer-mode stepwise --resume auto

Exit code: 0 when every rank exited 0 (only meaningful for no-fault
runs); otherwise the first failing rank's code (killed ranks surface as
128+signal). tests/test_chaos.py runs these scenarios with assertions;
this tool is the operator-facing way to reproduce one interactively.

``--list`` is the drift gate: tests/test_supervision.py pins that its
output, the ``FAULT_POINTS`` registry, and the ``maybe_fault()`` call
sites in the source all agree — a hook added without registry+docs (or
vice versa) fails the suite.
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from pytorch_distributed_mnist_tpu.parallel.launcher import (  # noqa: E402
    spawn_local,
)
from pytorch_distributed_mnist_tpu.runtime.supervision import (  # noqa: E402
    FAULT_ENV,
    FAULT_POINTS,
    TIMEOUT_ENV,
    FaultPlan,
)


def list_fault_points(file=sys.stdout) -> None:
    """One line per injectable point: ``name<TAB>description``."""
    for name in sorted(FAULT_POINTS):
        print(f"{name}\t{FAULT_POINTS[name]}", file=file)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="chaos",
        description="fault-injection twins for the run-supervision layer",
    )
    p.add_argument("--list", action="store_true",
                   help="enumerate injectable fault points and exit")
    p.add_argument("--fault", type=str, default=None,
                   metavar="POINT:HOST:KIND[:ARG]",
                   help="the fault to inject (see --list; kinds: kill, "
                        "raise, stall). Omit for a clean control run")
    p.add_argument("--nprocs", type=int, default=2,
                   help="local host processes to spawn (default 2)")
    p.add_argument("--agreement-timeout", type=float, default=15.0,
                   help="watchdog deadline handed to every rank via "
                        f"{TIMEOUT_ENV} (default 15s: chaos runs WANT "
                        "the watchdog — a hang is the bug under test)")
    p.add_argument("--timeout", type=float, default=600.0,
                   help="whole-run wall clock bound before every rank "
                        "is killed (default 600s)")
    p.add_argument("cli_args", nargs=argparse.REMAINDER,
                   help="arguments after -- go to tpu-mnist verbatim")
    args = p.parse_args(argv)

    if args.list:
        list_fault_points()
        return 0

    if args.fault:
        FaultPlan.parse(args.fault)  # fail fast with the spec's message
        os.environ[FAULT_ENV] = args.fault
    else:
        os.environ.pop(FAULT_ENV, None)
    os.environ[TIMEOUT_ENV] = str(args.agreement_timeout)

    cli_args = list(args.cli_args)
    if cli_args and cli_args[0] == "--":
        cli_args = cli_args[1:]
    print(f"chaos: spawning {args.nprocs} ranks"
          + (f", fault {args.fault}" if args.fault else " (control run)")
          + f", agreement timeout {args.agreement_timeout:g}s",
          file=sys.stderr)
    return spawn_local(args.nprocs, cli_args, timeout=args.timeout)


if __name__ == "__main__":
    sys.exit(main())
