"""MXU-bound kernel benchmarks: flash vs dense attention, fused Adam vs optax.

The CNN headline bench (bench.py) is HBM-bound at 1.9 MFLOP/image — its MFU
is a rounding error by construction and says nothing about the Pallas
kernels. This runner measures the kernels on workloads where the MXU is the
bottleneck, answering the only question that matters for them: do the
first-party kernels beat (or match) XLA's own lowering?

- Attention: ``ops.pallas.flash.flash_attention`` vs the dense XLA path
  (``ops.attention.full_attention``) at T in {256, 1024, 4096}, fwd+bwd
  (the training configuration), constant token budget so every row fits
  HBM. Reports per-config times, speedup, and analytic-FLOPs MFU.
- Optimizer: ``ops.pallas.adam.pallas_adam`` vs ``optax.adam`` on a ~13M
  parameter pytree (transformer-block-shaped leaves), update step only.

Prints ONE JSON line. Runs standalone on whatever backend is up (the
watcher invokes it on TPU after a successful bench capture); ``--quick``
shrinks shapes for the hermetic CPU smoke test (flash falls back to
interpret mode off-TPU, so only correctness-of-the-harness is asserted
there, never perf).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class MeasurementInvalid(RuntimeError):
    """A timing that violates a physical bound (MFU or HBM-bandwidth
    utilization above 100%): the device sync did not actually wait for
    execution, so every number in the run is garbage. Raised past the
    partial-result handlers in ``main`` — the process exits nonzero and
    the output carries ``"invalid"`` instead of the ``"sync":
    "host_read"`` validity marker, so a watcher gating on rc==0 can
    never publish the capture as evidence."""


# Per-chip peak HBM bandwidth, bytes/sec, by TPU generation (public spec
# sheets). Used only as an impossibility bound for HBM-bound kernels
# (the Adam update): measured time below bytes_moved/peak_bw is garbage.
_PEAK_HBM_BW = [
    ("v6", 1638e9),  # Trillium
    ("v5p", 2765e9),
    ("v5 lite", 819e9),
    ("v5e", 819e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
]


def _peak_hbm_bw(device_kind: str):
    fake = os.environ.get("BENCH_FAKE_HBM_BW")
    if fake:  # test-only: see bench._peak_flops
        return float(fake)
    kind = device_kind.lower()
    for key, bw in _PEAK_HBM_BW:
        if key in kind:
            return bw
    return None


def check_mfu(label: str, secs: float, flops: float, peak):
    """MFU for a row, guarded: >100% of peak is physically impossible —
    it means the device sync did not wait for execution (exactly how
    round 3's kernels.json capture went bad). Shared by this file and
    tools/sweep_flash.py so the bound and its message can never
    diverge. Returns None when the device kind has no known peak."""
    if not peak:
        return None
    mfu = flops / secs / peak
    if mfu > 1.0:
        raise MeasurementInvalid(
            f"impossible {label} MFU {mfu:.4g} (>100% of peak): "
            f"device sync did not wait for execution")
    return round(mfu, 4)


from bench import _fake_bounds  # noqa: E402 - single source for the
# test-only bound-override set (bench.py's children use the same one)


def _host_read(out) -> float:
    """Force a device→host roundtrip on one element of ``out``.

    Round-3 postmortem: ``jax.block_until_ready`` returned early on the
    proxied TPU link, and kernels.json recorded times 4-120× too small
    (up to 11,793% MFU).  A scalar read back to the host can only
    complete after every program queued ahead of it on the device stream
    has executed — the device runs programs in order — so a timestamp
    taken after this call is a true upper bound on execution end.  The
    scalar-index op is compiled during warmup (``_timeit`` calls this on
    the warmup output too), leaving only the ~2-byte transfer in the
    timed region.
    """
    import jax

    leaf = jax.tree_util.tree_leaves(out)[0]
    return float(leaf[(0,) * leaf.ndim])


def _timeit(fn, args, reps: int, iters: int) -> float:
    """Seconds per call: warmup (compile) then best-of-``reps`` means.

    Sync protocol is a host read of the last output (see ``_host_read``),
    never ``block_until_ready`` alone.
    """
    out = fn(*args)
    _host_read(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        _host_read(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def bench_attention(quick: bool, reps: int, iters: int) -> list:
    import jax
    import jax.numpy as jnp

    from bench import _peak_flops
    from pytorch_distributed_mnist_tpu.ops.attention import full_attention
    from pytorch_distributed_mnist_tpu.ops.pallas.flash import flash_attention

    # Constant ~8k-token budget: T grows, B shrinks, HBM footprint stays
    # bounded (the dense path still materializes (B,H,T,T) f32 scores —
    # 0.5 GB at the 4k row, the largest tensor in this file).
    configs = [(64, 2), (128, 1)] if quick else [(256, 32), (1024, 8), (4096, 2)]
    heads, dim = (2, 64) if quick else (8, 128)
    peak = _peak_flops(jax.devices()[0].device_kind)

    def make_loss(attn):
        def loss(q, k, v):
            return jnp.sum(attn(q, k, v).astype(jnp.float32))
        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    flash_g = make_loss(flash_attention)
    dense_g = make_loss(full_attention)

    rows = []
    for t, b in configs:
        key = jax.random.key(0)
        kq, kk, kv = jax.random.split(key, 3)
        shape = (b, t, heads, dim)
        q = jax.random.normal(kq, shape, jnp.bfloat16)
        k = jax.random.normal(kk, shape, jnp.bfloat16)
        v = jax.random.normal(kv, shape, jnp.bfloat16)
        flash_s = _timeit(flash_g, (q, k, v), reps, iters)
        dense_s = _timeit(dense_g, (q, k, v), reps, iters)
        # Analytic matmul FLOPs: fwd QK^T + PV = 4*B*H*T^2*D; bwd recomputes
        # scores and forms dV, dP, dQ, dK — 4 more T^2 matmuls plus the
        # recompute = ~12*B*H*T^2*D total for fwd+bwd.
        flops = 12.0 * b * heads * t * t * dim
        rows.append({
            "seq_len": t, "batch": b, "heads": heads, "head_dim": dim,
            "flash_ms": round(flash_s * 1e3, 3),
            "dense_ms": round(dense_s * 1e3, 3),
            "flash_over_dense_speedup": round(dense_s / flash_s, 3),
            "flash_mfu": check_mfu(f"flash T={t}", flash_s, flops, peak),
            "dense_mfu": check_mfu(f"dense T={t}", dense_s, flops, peak),
        })
    return rows


def bench_adam(quick: bool, reps: int, iters: int) -> dict:
    import jax
    import jax.numpy as jnp
    import optax

    from pytorch_distributed_mnist_tpu.ops.pallas.adam import pallas_adam

    # Transformer-block-shaped leaves, ~13.6M params (>=10M per VERDICT):
    # one big square projection, an MLP up/down pair, and small vectors so
    # the kernel's ragged-tail path is exercised too.
    shapes = ([(256, 256), (256, 512), (512, 256), (256,)] if quick else
              [(3072, 3072), (3072, 680), (680, 3072), (3072,), (680,)])
    key = jax.random.key(1)
    params = {}
    grads = {}
    for i, s in enumerate(shapes):
        key, k1, k2 = jax.random.split(key, 3)
        params[f"w{i}"] = jax.random.normal(k1, s, jnp.float32) * 0.02
        grads[f"w{i}"] = jax.random.normal(k2, s, jnp.float32)
    n_params = sum(int(jnp.size(p)) for p in params.values())

    def step_time(tx):
        state = tx.init(params)

        @jax.jit
        def step(state, grads, params):
            updates, state = tx.update(grads, state, params)
            return optax.apply_updates(params, updates), state

        return _timeit(step, (state, grads, params), reps, iters)

    optax_s = step_time(optax.adam(1e-3))
    fused_s = step_time(pallas_adam(1e-3))
    out = {
        "n_params": n_params,
        "optax_ms": round(optax_s * 1e3, 3),
        "fused_ms": round(fused_s * 1e3, 3),
        "fused_over_optax_speedup": round(optax_s / fused_s, 3),
    }
    # Impossibility bound for this HBM-bound kernel (the attention MFU
    # check can't see it): any correct f32 Adam step must move at least
    # reads of p,g,m,v plus writes of p,m,v = 7 arrays x 4 bytes/param
    # through HBM. Faster than peak bandwidth allows = the sync lied.
    bw = _peak_hbm_bw(jax.devices()[0].device_kind)
    if bw:
        floor_s = 28.0 * n_params / bw
        for name, secs in (("optax", optax_s), ("fused", fused_s)):
            frac = floor_s / secs  # fraction of peak HBM bw; must be <= 1
            out[f"{name}_hbm_frac"] = round(frac, 4)
            if frac > 1.0:
                raise MeasurementInvalid(
                    f"impossible adam {name} time {secs * 1e3:.3f} ms: "
                    f"{frac:.2f}x peak HBM bandwidth for the minimum "
                    f"{28 * n_params} bytes moved; sync did not wait")
    return out


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--quick", action="store_true",
                   help="tiny shapes for the hermetic CPU smoke test")
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--iters", type=int, default=10)
    args = p.parse_args()

    import jax

    from bench import configure_jax

    configure_jax(jax)

    device = jax.devices()[0]
    fakes = _fake_bounds()
    if fakes and device.platform == "tpu":
        # A leaked test override would make a real capture's physical
        # bounds meaningless while still carrying the validity marker.
        print(json.dumps({
            "metric": "pallas_kernel_vs_xla", "backend": device.platform,
            "invalid": f"test-only bound overrides set on a real TPU "
                       f"run: {sorted(fakes)}"}))
        sys.exit(1)
    out = {
        "metric": "pallas_kernel_vs_xla",
        "backend": device.platform,
        "device_kind": device.device_kind,
        "quick": args.quick,
        # Provenance: which sync protocol produced these times. host_read
        # = a scalar fetched from device per rep (cannot complete before
        # execution does); the round-3 capture that lacked this field
        # used block_until_ready and is invalid (see _host_read).
        "sync": "host_read",
    }
    if fakes:
        out["fake_bounds"] = fakes  # test-only run, never evidence
    try:
        try:
            out["attention_fwd_bwd"] = bench_attention(
                args.quick, args.reps, args.iters)
        except MeasurementInvalid:
            raise  # physical-bound violation: whole run is garbage
        except Exception as exc:  # noqa: BLE001 - partial results still print
            out["attention_error"] = repr(exc)
        try:
            out["adam_update"] = bench_adam(args.quick, args.reps, args.iters)
        except MeasurementInvalid:
            raise
        except Exception as exc:  # noqa: BLE001
            out["adam_error"] = repr(exc)
    except MeasurementInvalid as exc:
        # Strip the validity marker, stamp the diagnosis, exit nonzero:
        # a watcher that gates publication on rc==0 can never turn this
        # run into kernels.json, and even a raw stdout redirect carries
        # "invalid" instead of "sync": "host_read".
        out.pop("sync", None)
        out["invalid"] = str(exc)
        print(json.dumps(out))
        sys.exit(1)
    print(json.dumps(out))
    if "attention_error" in out or "adam_error" in out:
        # Partial results printed for diagnosis, but a capture missing
        # rows must not pass an rc==0 publication gate (the watcher
        # would mark the item done and never retry a transient failure).
        sys.exit(2)


if __name__ == "__main__":
    main()
