"""MXU-bound kernel benchmarks: flash vs dense attention, fused Adam vs optax.

The CNN headline bench (bench.py) is HBM-bound at 1.9 MFLOP/image — its MFU
is a rounding error by construction and says nothing about the Pallas
kernels. This runner measures the kernels on workloads where the MXU is the
bottleneck, answering the only question that matters for them: do the
first-party kernels beat (or match) XLA's own lowering?

- Attention: ``ops.pallas.flash.flash_attention`` vs the dense XLA path
  (``ops.attention.full_attention``) at T in {256, 1024, 4096}, fwd+bwd
  (the training configuration), constant token budget so every row fits
  HBM. Reports per-config times, speedup, and analytic-FLOPs MFU.
- Optimizer: ``ops.pallas.adam.pallas_adam`` vs ``optax.adam`` on a ~13M
  parameter pytree (transformer-block-shaped leaves), update step only.

Prints ONE JSON line. Runs standalone on whatever backend is up (the
watcher invokes it on TPU after a successful bench capture); ``--quick``
shrinks shapes for the hermetic CPU smoke test (flash falls back to
interpret mode off-TPU, so only correctness-of-the-harness is asserted
there, never perf).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _timeit(fn, args, reps: int, iters: int) -> float:
    """Seconds per call: warmup (compile) then best-of-``reps`` means."""
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def bench_attention(quick: bool, reps: int, iters: int) -> list:
    import jax
    import jax.numpy as jnp

    from bench import _peak_flops
    from pytorch_distributed_mnist_tpu.ops.attention import full_attention
    from pytorch_distributed_mnist_tpu.ops.pallas.flash import flash_attention

    # Constant ~8k-token budget: T grows, B shrinks, HBM footprint stays
    # bounded (the dense path still materializes (B,H,T,T) f32 scores —
    # 0.5 GB at the 4k row, the largest tensor in this file).
    configs = [(64, 2), (128, 1)] if quick else [(256, 32), (1024, 8), (4096, 2)]
    heads, dim = (2, 64) if quick else (8, 128)
    peak = _peak_flops(jax.devices()[0].device_kind)

    def make_loss(attn):
        def loss(q, k, v):
            return jnp.sum(attn(q, k, v).astype(jnp.float32))
        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    flash_g = make_loss(flash_attention)
    dense_g = make_loss(full_attention)

    rows = []
    for t, b in configs:
        key = jax.random.key(0)
        kq, kk, kv = jax.random.split(key, 3)
        shape = (b, t, heads, dim)
        q = jax.random.normal(kq, shape, jnp.bfloat16)
        k = jax.random.normal(kk, shape, jnp.bfloat16)
        v = jax.random.normal(kv, shape, jnp.bfloat16)
        flash_s = _timeit(flash_g, (q, k, v), reps, iters)
        dense_s = _timeit(dense_g, (q, k, v), reps, iters)
        # Analytic matmul FLOPs: fwd QK^T + PV = 4*B*H*T^2*D; bwd recomputes
        # scores and forms dV, dP, dQ, dK — 4 more T^2 matmuls plus the
        # recompute = ~12*B*H*T^2*D total for fwd+bwd.
        flops = 12.0 * b * heads * t * t * dim
        rows.append({
            "seq_len": t, "batch": b, "heads": heads, "head_dim": dim,
            "flash_ms": round(flash_s * 1e3, 3),
            "dense_ms": round(dense_s * 1e3, 3),
            "flash_over_dense_speedup": round(dense_s / flash_s, 3),
            "flash_mfu": round(flops / flash_s / peak, 4) if peak else None,
            "dense_mfu": round(flops / dense_s / peak, 4) if peak else None,
        })
    return rows


def bench_adam(quick: bool, reps: int, iters: int) -> dict:
    import jax
    import jax.numpy as jnp
    import optax

    from pytorch_distributed_mnist_tpu.ops.pallas.adam import pallas_adam

    # Transformer-block-shaped leaves, ~13.6M params (>=10M per VERDICT):
    # one big square projection, an MLP up/down pair, and small vectors so
    # the kernel's ragged-tail path is exercised too.
    shapes = ([(256, 256), (256, 512), (512, 256), (256,)] if quick else
              [(3072, 3072), (3072, 680), (680, 3072), (3072,), (680,)])
    key = jax.random.key(1)
    params = {}
    grads = {}
    for i, s in enumerate(shapes):
        key, k1, k2 = jax.random.split(key, 3)
        params[f"w{i}"] = jax.random.normal(k1, s, jnp.float32) * 0.02
        grads[f"w{i}"] = jax.random.normal(k2, s, jnp.float32)
    n_params = sum(int(jnp.size(p)) for p in params.values())

    def step_time(tx):
        state = tx.init(params)

        @jax.jit
        def step(state, grads, params):
            updates, state = tx.update(grads, state, params)
            return optax.apply_updates(params, updates), state

        return _timeit(step, (state, grads, params), reps, iters)

    optax_s = step_time(optax.adam(1e-3))
    fused_s = step_time(pallas_adam(1e-3))
    return {
        "n_params": n_params,
        "optax_ms": round(optax_s * 1e3, 3),
        "fused_ms": round(fused_s * 1e3, 3),
        "fused_over_optax_speedup": round(optax_s / fused_s, 3),
    }


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--quick", action="store_true",
                   help="tiny shapes for the hermetic CPU smoke test")
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--iters", type=int, default=10)
    args = p.parse_args()

    import jax

    from bench import configure_jax

    configure_jax(jax)

    device = jax.devices()[0]
    out = {
        "metric": "pallas_kernel_vs_xla",
        "backend": device.platform,
        "device_kind": device.device_kind,
        "quick": args.quick,
    }
    try:
        out["attention_fwd_bwd"] = bench_attention(
            args.quick, args.reps, args.iters)
    except Exception as exc:  # noqa: BLE001 - partial results still print
        out["attention_error"] = repr(exc)
    try:
        out["adam_update"] = bench_adam(args.quick, args.reps, args.iters)
    except Exception as exc:  # noqa: BLE001
        out["adam_error"] = repr(exc)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
