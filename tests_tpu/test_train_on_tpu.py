"""On-hardware training smoke: the full driver on the real chip.

The hermetic suite proves correctness on virtual CPU devices; this proves
the same driver actually runs on TPU silicon — bf16 convs on the MXU, the
scan-epoch program, checkpoint write — and that throughput is in the
expected range for the device (a tunnel/backend regression would show up
as an order-of-magnitude drop).
"""

import numpy as np

from pytorch_distributed_mnist_tpu.cli import build_parser, run


def test_cnn_trains_on_tpu(tmp_path):
    summary = run(build_parser().parse_args([
        "--dataset", "synthetic", "--model", "cnn", "--epochs", "2",
        "--batch-size", "512", "--synthetic-train-size", "4096",
        "--synthetic-test-size", "1024", "--seed", "1",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--root", str(tmp_path / "data"),
    ]))
    assert summary["epochs_run"] == 2
    # learns: accuracy well above chance by epoch 1
    assert summary["history"][-1]["test_acc"] > 0.5
    # chip-scale throughput: even through the tunnel the v5e does
    # hundreds of thousands of images/sec; 10k is a generous floor that
    # still catches a silent CPU fallback (~10-1000 img/s). Assert on the
    # LAST epoch's rate: this smoke run is 8 steps/epoch, so the
    # cumulative figure is ~95% epoch-0 compile time (measured 661 img/s
    # on a chip benching 375k — the 2026-07-31 capture).
    assert summary["images_per_sec_per_chip_last_epoch"] > 10_000
    assert (tmp_path / "ckpt" / "model_best.npz").exists()


def test_device_gather_on_tpu(tmp_path):
    """--epoch-gather device on silicon: the dataset stays resident in HBM
    and each scan tick gathers with jnp.take; per-epoch host traffic drops
    to the index matrix. Trajectory must match the host-gather run
    exactly (same programs, same data — tests/test_device_gather.py pins
    this on CPU; here we pin it through the tunnel)."""
    common = [
        "--dataset", "synthetic", "--model", "cnn", "--epochs", "2",
        "--batch-size", "512", "--synthetic-train-size", "4096",
        "--synthetic-test-size", "1024", "--seed", "1",
        "--root", str(tmp_path / "data"),
    ]
    host = run(build_parser().parse_args(
        common + ["--checkpoint-dir", str(tmp_path / "h")]))
    dev = run(build_parser().parse_args(
        common + ["--checkpoint-dir", str(tmp_path / "d"),
                  "--epoch-gather", "device"]))
    assert dev["history"] == host["history"]
    assert dev["images_per_sec_per_chip_last_epoch"] > 10_000


def test_all_first_party_kernels_train_on_tpu(tmp_path):
    """One run exercising every first-party Pallas kernel in the real
    training loop on silicon: fused cross-entropy (--loss fused) and the
    fused Adam update (--optimizer adam_pallas). Numerics: the loss
    trajectory must match the XLA-path run to bf16-training tolerance."""
    common = [
        "--dataset", "synthetic", "--model", "cnn", "--epochs", "1",
        "--batch-size", "512", "--synthetic-train-size", "2048",
        "--synthetic-test-size", "512", "--seed", "1",
        "--root", str(tmp_path / "data"),
    ]
    base = run(build_parser().parse_args(
        common + ["--checkpoint-dir", str(tmp_path / "a")]))
    fused = run(build_parser().parse_args(
        common + ["--checkpoint-dir", str(tmp_path / "b"),
                  "--loss", "fused", "--optimizer", "adam_pallas"]))
    assert fused["epochs_run"] == 1
    np.testing.assert_allclose(
        fused["history"][0]["train_loss"],
        base["history"][0]["train_loss"], rtol=0.05)
    assert abs(fused["history"][0]["test_acc"]
               - base["history"][0]["test_acc"]) < 0.05
