"""Pallas kernels on REAL TPU: Mosaic compile + numerics vs XLA oracles.

The hermetic suite (tests/test_pallas_kernels.py) pins the same numerics in
interpret mode; this suite is the hardware half the advisor asked for —
it catches Mosaic-only failures (block tiling rules, SMEM refs, lane
alignment for the ViT head dims D=16/32) that interpret mode cannot see.

Oracle comparisons run under ``jax_default_matmul_precision=highest``
because the dense oracle's MXU matmuls otherwise run bf16 passes and the
~5e-3 "error" would be the oracle's, not the kernel's.
"""

import jax
import jax.numpy as jnp
import optax
import pytest

from pytorch_distributed_mnist_tpu.ops.attention import full_attention
from pytorch_distributed_mnist_tpu.ops.pallas.adam import pallas_adam
from pytorch_distributed_mnist_tpu.ops.pallas.flash import flash_attention


@pytest.fixture(autouse=True)
def _highest_precision():
    with jax.default_matmul_precision("highest"):
        yield


# ViT head dim D=16 (sub-128-lane, the flagged Mosaic hazard) and a ragged
# T requiring pad+mask. Kept to two shapes: each case costs several real
# Mosaic compiles through the chip tunnel (~30s each); the full 4-shape
# sweep lives in the commit history (all passed 2026-07-29).
SHAPES = [(2, 64, 4, 16), (1, 200, 2, 32)]


@pytest.mark.parametrize("shape", SHAPES)
@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_backward_on_tpu(shape, causal):
    b, t, h, d = shape
    k1, k2, k3 = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(k1, shape, jnp.float32)
    k = jax.random.normal(k2, shape, jnp.float32)
    v = jax.random.normal(k3, shape, jnp.float32)

    def loss(f):
        return lambda *a: jnp.sum(jnp.sin(f(*a, causal=causal)))

    out = flash_attention(q, k, v, causal=causal)
    ref = full_attention(q, k, v, causal=causal)
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-5

    grads = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    grads_ref = jax.grad(loss(full_attention), argnums=(0, 1, 2))(q, k, v)
    for g, gr in zip(grads, grads_ref):
        assert float(jnp.max(jnp.abs(g - gr))) < 2e-3


def test_fused_adam_on_tpu_matches_optax():
    params = {
        "w": jnp.ones((3, 3, 1, 32)),
        "b": jnp.zeros((10,)),
        "fc": jnp.ones((12544, 128)),
        "s": jnp.ones((1,)),
    }
    grads = jax.tree.map(lambda p: jnp.full_like(p, 0.1), params)
    opt_a, opt_b = pallas_adam(1e-3), optax.adam(1e-3)
    sa, sb = opt_a.init(params), opt_b.init(params)
    for _ in range(3):
        ua, sa = opt_a.update(grads, sa)
        ub, sb = opt_b.update(grads, sb)
        for x, y in zip(jax.tree.leaves(ua), jax.tree.leaves(ub)):
            assert float(jnp.max(jnp.abs(x - y))) < 1e-6


def test_fused_xent_on_tpu_matches_oracle():
    """Mosaic compile of the xent fwd+bwd kernels; value and grad vs the
    XLA oracle. C=10 (sub-128-lane block) and a ragged batch exercise the
    pad/mask path on real tiling rules."""
    from pytorch_distributed_mnist_tpu.ops.loss import (
        cross_entropy_per_example,
    )
    from pytorch_distributed_mnist_tpu.ops.pallas.xent import (
        fused_cross_entropy_per_example,
    )

    k1, k2 = jax.random.split(jax.random.key(1))
    for b in (256, 300):
        logits = jax.random.normal(k1, (b, 10), jnp.float32) * 5
        labels = jax.random.randint(k2, (b,), 0, 10)
        g = jax.random.normal(k2, (b,), jnp.float32)

        want, vjp_o = jax.vjp(
            lambda l: cross_entropy_per_example(l, labels), logits)
        got, vjp_k = jax.vjp(
            lambda l: fused_cross_entropy_per_example(l, labels), logits)
        assert float(jnp.max(jnp.abs(got - want))) < 1e-5
        dl_want = vjp_o(g)[0]
        dl_got = vjp_k(g)[0]
        # Backward tolerance is wider than interpret mode's 1e-5
        # (tests/test_pallas_kernels.py): the kernel computes softmax as
        # one exp(l - lse) while the oracle's autodiff divides
        # exp(l - m) by the saved sum, and the chip's f32 transcendental
        # rounding differs from the host's — measured max divergence
        # 9.5e-5 on these x5-scaled logits (2026-07-31), algorithmic
        # regressions are caught at 1e-5 hermetically.
        assert float(jnp.max(jnp.abs(dl_got - dl_want))) < 2e-4
