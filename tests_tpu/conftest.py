"""On-hardware TPU test suite (run separately from the hermetic tests/).

``tests/`` forces 8 virtual CPU devices so every sharding property is
checkable without a pod — but that leaves the Pallas kernels' real Mosaic
compile path unexercised (round-1 advisor finding: both kernels had only
ever run in interpret mode, and the flash lse row-block layout did in fact
fail Mosaic's (8, 128) tiling check on first real-TPU contact).

Run with:  python -m pytest tests_tpu/ -q
Skips cleanly (doesn't fail) when no TPU backend is reachable.
"""

import os
import subprocess
import sys

import pytest

# Share the repo's persistent XLA compile cache (same dir bench.py and
# tools/tpu_watch.sh use): the watcher's capture run warms it, and this
# suite's on-chip compiles (minutes through the tunnel) amortize across
# sessions instead of re-paying every time the chip answers.
_cache = os.environ.get(
    "BENCH_COMPILE_CACHE",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".xla_cache"))
if _cache:
    import jax

    jax.config.update("jax_compilation_cache_dir", _cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

_PROBE = (
    # Listing devices is not enough: a wedged tunnel can enumerate the
    # chip while every execution hangs (observed 2026-07-30). The probe
    # must round-trip a real computation.
    "import jax; assert jax.default_backend() == 'tpu' or any("
    "d.platform == 'tpu' for d in jax.devices()); "
    "import jax.numpy as jnp; "
    "assert float(jnp.sum(jnp.ones((8, 8)))) == 64.0"
)


def _tpu_available() -> bool:
    # Probe in a CHILD with a hard timeout: when the chip tunnel is wedged,
    # backend init HANGS rather than failing, and an in-process probe would
    # hang collection (and poison this process's jax backend state even on
    # success-after-wait).
    try:
        return subprocess.run(
            [sys.executable, "-c", _PROBE],
            capture_output=True, timeout=60,
        ).returncode == 0
    except (subprocess.TimeoutExpired, OSError):
        return False


def pytest_collection_modifyitems(config, items):
    if not _tpu_available():
        skip = pytest.mark.skip(reason="no TPU backend reachable")
        for item in items:
            item.add_marker(skip)
