"""On-hardware TPU test suite (run separately from the hermetic tests/).

``tests/`` forces 8 virtual CPU devices so every sharding property is
checkable without a pod — but that leaves the Pallas kernels' real Mosaic
compile path unexercised (round-1 advisor finding: both kernels had only
ever run in interpret mode, and the flash lse row-block layout did in fact
fail Mosaic's (8, 128) tiling check on first real-TPU contact).

Run with:  python -m pytest tests_tpu/ -q
Skips cleanly (doesn't fail) when no TPU backend is reachable.
"""

import pytest


def _tpu_available() -> bool:
    try:
        import jax

        return jax.default_backend() == "tpu" or any(
            d.platform == "tpu" for d in jax.devices()
        )
    except Exception:
        return False


def pytest_collection_modifyitems(config, items):
    if not _tpu_available():
        skip = pytest.mark.skip(reason="no TPU backend reachable")
        for item in items:
            item.add_marker(skip)
