// Native data-path backend for the TPU MNIST framework.
//
// The reference gets its native data machinery from torch's DataLoader
// worker processes (multi_proc_single_gpu.py:156 num_workers) and
// torchvision's C IO. This library is the TPU framework's first-party
// equivalent: IDX parsing (raw + gzip), uint8->normalized-float32 transform,
// and epoch batch gathering, all multithreaded over a caller-chosen worker
// count (the CLI's -j/--workers flag).
//
// Exposed as a plain C ABI consumed from Python via ctypes (no pybind11 in
// this environment). Buffer-returning calls allocate with malloc; the caller
// must release with tm_free.

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

#include <zlib.h>

namespace {

// Run body(start, end) over [0, n) split across `workers` threads.
void parallel_for(int64_t n, int workers, void (*body)(int64_t, int64_t, void*),
                  void* ctx) {
  if (workers < 1) workers = 1;
  if (workers == 1 || n < 1024) {
    body(0, n, ctx);
    return;
  }
  std::vector<std::thread> threads;
  int64_t chunk = (n + workers - 1) / workers;
  for (int w = 0; w < workers; ++w) {
    int64_t start = w * chunk;
    int64_t end = start + chunk < n ? start + chunk : n;
    if (start >= end) break;
    threads.emplace_back(body, start, end, ctx);
  }
  for (auto& t : threads) t.join();
}

bool read_file(const char* path, std::vector<uint8_t>& out) {
  size_t len = strlen(path);
  bool gz = len > 3 && strcmp(path + len - 3, ".gz") == 0;
  if (gz) {
    gzFile f = gzopen(path, "rb");
    if (!f) return false;
    uint8_t buf[1 << 16];
    int n;
    while ((n = gzread(f, buf, sizeof(buf))) > 0) out.insert(out.end(), buf, buf + n);
    gzclose(f);
    return n == 0;
  }
  FILE* f = fopen(path, "rb");
  if (!f) return false;
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  fseek(f, 0, SEEK_SET);
  if (size < 0) {
    fclose(f);
    return false;
  }
  out.resize(size);
  bool ok = fread(out.data(), 1, size, f) == static_cast<size_t>(size);
  fclose(f);
  return ok;
}

uint32_t be32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) | (uint32_t(p[2]) << 8) |
         uint32_t(p[3]);
}

}  // namespace

extern "C" {

// Load a uint8 IDX file (raw or .gz) in ONE read+inflate pass.
// On success returns a malloc'd payload buffer (release with tm_free),
// fills dims[0..*ndim) and *count. Returns nullptr on any error: unreadable
// file, bad magic, non-uint8 dtype, ndim > max_dims, or truncated payload.
uint8_t* tm_idx_load(const char* path, int64_t* dims, int* ndim, int max_dims,
                     int64_t* count) {
  std::vector<uint8_t> data;
  if (!read_file(path, data) || data.size() < 4) return nullptr;
  if (data[0] != 0 || data[1] != 0 || data[2] != 0x08) return nullptr;
  int nd = data[3];
  if (nd > max_dims) return nullptr;
  size_t header = 4 + size_t(4) * nd;
  if (data.size() < header) return nullptr;
  int64_t total = 1;
  for (int i = 0; i < nd; ++i) {
    dims[i] = be32(&data[4 + 4 * i]);
    total *= dims[i];
  }
  if (data.size() - header < size_t(total)) return nullptr;
  uint8_t* out = static_cast<uint8_t*>(malloc(total > 0 ? total : 1));
  if (!out) return nullptr;
  memcpy(out, data.data() + header, total);
  *ndim = nd;
  *count = total;
  return out;
}

void tm_free(void* p) { free(p); }

struct NormCtx {
  const uint8_t* in;
  float* out;
  float mean;
  float stddev;
};

// out[i] = (in[i]/255 - mean) / std, multithreaded. The div/sub/div
// sequence is deliberately the NumPy fallback's float32 op sequence
// (``x/255.0 - MEAN) / STD`` in data/mnist.py) rather than a fused
// scale*x+offset: identical rounding at every step makes the native
// path BITWISE-equal to the fallback, so which engine ran can never
// show up in a trajectory (pinned by tests/test_native.py).
int tm_normalize(const uint8_t* in, float* out, int64_t n, float mean,
                 float stddev, int workers) {
  NormCtx ctx{in, out, mean, stddev};
  parallel_for(
      n, workers,
      [](int64_t start, int64_t end, void* p) {
        auto* c = static_cast<NormCtx*>(p);
        for (int64_t i = start; i < end; ++i)
          c->out[i] = (float(c->in[i]) / 255.0f - c->mean) / c->stddev;
      },
      &ctx);
  return 0;
}

struct GatherCtx {
  const float* images;    // (N, row) flattened
  const int32_t* labels;  // (N,)
  const int64_t* indices; // (M,)
  float* out_images;      // (M, row)
  int32_t* out_labels;    // (M,)
  int64_t row;
  int64_t n;
  std::atomic<bool> oob{false};
};

// Gather rows: out_images[j] = images[indices[j]], multithreaded over j.
// This is the epoch-staging hot path (stacked_epoch): one pass builds the
// (steps*batch, row) array fed to the device in a single transfer.
int tm_gather(const float* images, const int32_t* labels, const int64_t* indices,
              int64_t m, int64_t row, int64_t n, float* out_images,
              int32_t* out_labels, int workers) {
  GatherCtx ctx;
  ctx.images = images;
  ctx.labels = labels;
  ctx.indices = indices;
  ctx.out_images = out_images;
  ctx.out_labels = out_labels;
  ctx.row = row;
  ctx.n = n;
  parallel_for(
      m, workers,
      [](int64_t start, int64_t end, void* p) {
        auto* c = static_cast<GatherCtx*>(p);
        for (int64_t j = start; j < end; ++j) {
          int64_t src = c->indices[j];
          if (src < 0 || src >= c->n) {
            c->oob.store(true, std::memory_order_relaxed);
            continue;
          }
          memcpy(c->out_images + j * c->row, c->images + src * c->row,
                 c->row * sizeof(float));
          c->out_labels[j] = c->labels[src];
        }
      },
      &ctx);
  return ctx.oob.load(std::memory_order_relaxed) ? -1 : 0;
}

struct PadCtx {
  const float* src;     // (rows, row) contiguous
  float* dst;           // (bucket_rows, row) contiguous
  int64_t rows;         // real rows to copy
  int64_t row;          // elements per row
};

// Serve-dispatch staging: dst[0:rows] = src, dst[rows:bucket_rows] = 0,
// multithreaded over the BUCKET rows. This is the pad-into-staging-buffer
// copy the inference engine runs per dispatched batch (serve/engine.py);
// the zero-fill of the tail matches the NumPy fallback bit-for-bit (both
// are all-zero float32 rows).
int tm_pad_copy(const float* src, int64_t rows, int64_t row, float* dst,
                int64_t bucket_rows, int workers) {
  if (rows < 0 || rows > bucket_rows || row < 0) return -1;
  PadCtx ctx{src, dst, rows, row};
  parallel_for(
      bucket_rows, workers,
      [](int64_t start, int64_t end, void* p) {
        auto* c = static_cast<PadCtx*>(p);
        for (int64_t j = start; j < end; ++j) {
          if (j < c->rows) {
            memcpy(c->dst + j * c->row, c->src + j * c->row,
                   c->row * sizeof(float));
          } else {
            memset(c->dst + j * c->row, 0, c->row * sizeof(float));
          }
        }
      },
      &ctx);
  return 0;
}

struct QuantCtx {
  const float* in;
  int8_t* out;
  float inv_scale;
};

// float32 -> int8 symmetric quantization: q = clip(rne(x / scale), -127, 127),
// multithreaded. nearbyintf under the default FE_TONEAREST mode rounds to
// nearest EVEN — exactly NumPy's np.rint — so the fallback equivalence is
// bitwise (pinned by tests/test_native.py). This is the int8-activation
// serve staging hot path: the host quantizes the normalized batch before
// the H2D transfer, quartering the staged bytes.
int tm_quant_i8(const float* in, int8_t* out, int64_t n, float scale,
                int workers) {
  if (scale <= 0.0f) return -1;
  QuantCtx ctx{in, out, 1.0f / scale};
  parallel_for(
      n, workers,
      [](int64_t start, int64_t end, void* p) {
        auto* c = static_cast<QuantCtx*>(p);
        for (int64_t i = start; i < end; ++i) {
          float q = nearbyintf(c->in[i] * c->inv_scale);
          if (q != q) q = 0.0f;  // NaN -> 0 (static_cast of NaN is UB;
                                 // the NumPy fallback pins the same 0)
          if (q > 127.0f) q = 127.0f;   // +inf clips here
          if (q < -127.0f) q = -127.0f; // -inf clips here
          c->out[i] = static_cast<int8_t>(q);
        }
      },
      &ctx);
  return 0;
}

struct DequantCtx {
  const int8_t* in;
  float* out;
  float scale;
};

// int8 -> float32 dequantization: x = float(q) * scale, multithreaded —
// one f32 multiply per element, the same op sequence as the NumPy
// fallback (astype(float32) * scale), so the equivalence is bitwise.
int tm_dequant_f32(const int8_t* in, float* out, int64_t n, float scale,
                   int workers) {
  DequantCtx ctx{in, out, scale};
  parallel_for(
      n, workers,
      [](int64_t start, int64_t end, void* p) {
        auto* c = static_cast<DequantCtx*>(p);
        for (int64_t i = start; i < end; ++i)
          c->out[i] = static_cast<float>(c->in[i]) * c->scale;
      },
      &ctx);
  return 0;
}

struct CastCtx {
  const double* in;
  float* out;
};

// float64 -> float32, multithreaded. A C double->float conversion rounds
// to nearest even, exactly what NumPy's astype(float32) does, so the
// fallback equivalence is bitwise.
int tm_cast_f32(const double* in, float* out, int64_t n, int workers) {
  CastCtx ctx{in, out};
  parallel_for(
      n, workers,
      [](int64_t start, int64_t end, void* p) {
        auto* c = static_cast<CastCtx*>(p);
        for (int64_t i = start; i < end; ++i)
          c->out[i] = static_cast<float>(c->in[i]);
      },
      &ctx);
  return 0;
}

int tm_version() { return 4; }

}  // extern "C"
